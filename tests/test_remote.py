"""parquet_tpu.io.remote tests: the httpstub range server, HttpSource's
typed failure taxonomy, ObjectStoreSource re-signing, resilience-stack
composition, reader/dataset/daemon integration, and the issue's
acceptance pins:

  * a warm tiered-cache scan of an httpstub-served corpus reads ZERO
    source bytes (io counter-delta pin — the ROADMAP acceptance pin);
  * under the seeded fault sweep, HttpSource reads are typed-or-byte-
    identical vs the local source (never hung, never torn);
  * a daemon and a dataset sharing ONE tiered cache concurrently stay
    byte-identical.

The extended seed x fault sweep runs under `slow` (`make fuzz`); a seeded
fast subset rides tier-1."""

import io as _stdio
import json
import threading
import urllib.request

import numpy as np
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.data import ParquetDataset
from parquet_tpu.io import (
    FooterCache,
    HttpSource,
    ObjectStoreSource,
    ResilienceConfig,
    RetryingSource,
    SourceError,
    TieredCache,
    TransientSourceError,
    configure_resilience,
    open_source,
)
from parquet_tpu.testing.httpstub import RangeHttpStub
from parquet_tpu.utils import metrics

NOSLEEP = lambda s: None


@pytest.fixture(scope="module")
def blob():
    return (
        np.random.default_rng(11)
        .integers(0, 256, 1 << 17)
        .astype(np.uint8)
        .tobytes()
    )


@pytest.fixture(scope="module")
def corpus():
    """A 2-row-group parquet file as bytes + its decoded arrow table."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    t = pa.table(
        {
            "id": pa.array(np.arange(40_000, dtype=np.int64)),
            "v": pa.array(rng.standard_normal(40_000)),
            "tag": pa.array([f"t{i % 37}" for i in range(40_000)]),
        }
    )
    buf = _stdio.BytesIO()
    pq.write_table(t, buf, compression="snappy", row_group_size=16_384)
    return buf.getvalue(), t


class TestHttpStub:
    def test_range_semantics(self, blob):
        import http.client

        with RangeHttpStub(files={"a.bin": blob}) as stub:
            conn = http.client.HTTPConnection("127.0.0.1", stub.port)
            try:
                conn.request("GET", "/a.bin", headers={"Range": "bytes=10-19"})
                r = conn.getresponse()
                body = r.read()
                assert r.status == 206
                assert body == blob[10:20]
                assert (
                    r.headers["Content-Range"]
                    == f"bytes 10-19/{len(blob)}"
                )
                etag = r.headers["ETag"]
                # suffix range
                conn.request("GET", "/a.bin", headers={"Range": "bytes=-4"})
                r = conn.getresponse()
                assert r.status == 206 and r.read() == blob[-4:]
                # open-ended
                conn.request(
                    "GET", "/a.bin",
                    headers={"Range": f"bytes={len(blob) - 8}-"},
                )
                r = conn.getresponse()
                assert r.status == 206 and r.read() == blob[-8:]
                # unsatisfiable
                conn.request(
                    "GET", "/a.bin",
                    headers={"Range": f"bytes={len(blob)}-"},
                )
                r = conn.getresponse()
                r.read()
                assert r.status == 416
                # full GET + stable etag
                conn.request("GET", "/a.bin")
                r = conn.getresponse()
                assert r.status == 200 and r.read() == blob
                assert r.headers["ETag"] == etag
                # HEAD
                conn.request("HEAD", "/a.bin")
                r = conn.getresponse()
                r.read()
                assert r.status == 200
                assert int(r.headers["Content-Length"]) == len(blob)
                # 404
                conn.request("GET", "/nope")
                r = conn.getresponse()
                r.read()
                assert r.status == 404
            finally:
                conn.close()


class TestHttpSource:
    def test_reads_byte_identical(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            assert src.size() == len(blob)
            assert src.read_at(0, 64) == blob[:64]
            assert src.read_at(12345, 6789) == blob[12345 : 12345 + 6789]
            assert src.read_at(0, 0) == b""
            got = src.read_ranges(
                [(0, 128), (50_000, 256), (len(blob) - 16, 16)]
            )
            assert [bytes(b) for b in got] == [
                blob[:128], blob[50_000:50_256], blob[-16:],
            ]

    def test_read_counters_and_request_metric(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            s0 = metrics.snapshot()
            src.read_at(0, 1000)
            d = metrics.delta(s0)
            assert d.get("io_bytes_read_total", 0) == 1000
            assert d.get('io_http_requests_total{status="206"}', 0) == 1

    def test_connection_reuse(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            s0 = metrics.snapshot()
            for _ in range(5):
                src.read_at(0, 64)
            d = metrics.delta(s0)
            assert d.get('io_http_connections_total{event="new"}', 0) == 0
            assert d.get('io_http_connections_total{event="reused"}', 0) == 5

    def test_typed_404(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            with pytest.raises(SourceError) as ei:
                HttpSource(stub.url_for("missing.bin"))
            assert ei.value.code == "http_404"

    def test_past_eof_is_typed_without_a_round_trip(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            reqs = stub.requests
            with pytest.raises(SourceError):
                src.read_at(len(blob) - 4, 64)
            assert stub.requests == reqs  # no transport touch
            with pytest.raises(ValueError):
                src.read_at(-1, 4)

    def test_416_when_the_pinned_size_lies(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(
                stub.url_for("a.bin"), size=len(blob) + 100
            )
            with pytest.raises(SourceError) as ei:
                src.read_at(len(blob) + 10, 8)
            assert ei.value.code == "http_416"

    def test_5xx_is_transient_then_ladder_exhaustion_is_typed(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, permanent=True
        ) as stub:
            stub.permanent = False
            src = HttpSource(stub.url_for("a.bin"))
            stub.permanent = True
            with pytest.raises(TransientSourceError) as ei:
                src.read_at(0, 64)
            assert ei.value.code == "http_503"
            ladder = RetryingSource(src, attempts=3, sleep=NOSLEEP, seed=1)
            with pytest.raises(SourceError) as ei2:
                ladder.read_at(0, 64)
            assert ei2.value.code == "retry_exhausted"

    def test_truncated_body_is_transient_and_retryable(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, seed=5, short_rate=1.0
        ) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            stub.short_rate = 1.0
            with pytest.raises(TransientSourceError):
                src.read_at(0, 4096)
            # the ladder re-reads through intermittent truncation
            stub.short_rate = 0.5
            ladder = RetryingSource(
                src, attempts=8, sleep=NOSLEEP, seed=2
            )
            assert ladder.read_at(0, 4096) == blob[:4096]

    def test_dropped_connection_is_transient(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            stub.drop_rate = 1.0
            with pytest.raises(TransientSourceError) as ei:
                src.read_at(0, 64)
            assert ei.value.code == "transport"

    def test_rewritten_object_is_source_changed(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            stub.set_file("a.bin", bytes(reversed(blob)))  # new ETag
            with pytest.raises(SourceError) as ei:
                src.read_at(0, 64)
            assert ei.value.code == "source_changed"

    def test_if_range_downgrade_mid_scan_is_source_changed(self, blob):
        # PR 17: reads of a pinned-ETag source carry If-Range, so a server
        # whose object was rewritten MID-SCAN answers 200 + the full NEW
        # body instead of slicing stale-vs-new ranges together — and the
        # read surfaces as typed source_changed, never as mixed bytes
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            assert src.read_at(0, 64) == blob[:64]  # scan under way
            stub.set_file("a.bin", bytes(reversed(blob)))
            with pytest.raises(SourceError) as ei:
                src.read_at(64, 64)
            assert ei.value.code == "source_changed"

    def test_etag_less_rewrite_betrayed_by_content_length(self, blob):
        # a validator-less server (no ETag) that also ignores Range: the
        # only rewrite signal left is the 200's Content-Length vs the
        # pinned size — a size-changing rewrite must still be typed, not
        # silently sliced out of the wrong generation
        with RangeHttpStub(
            files={"a.bin": blob}, send_etag=False, ignore_range=True
        ) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            assert src.read_at(0, 64) == blob[:64]
            stub.set_file("a.bin", blob[: len(blob) // 2])
            with pytest.raises(SourceError) as ei:
                src.read_at(0, 64)
            assert ei.value.code == "source_changed"

    def test_head_less_server_stat_fallback(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, reject_head=True
        ) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            assert src.size() == len(blob)
            assert src.read_at(7, 9) == blob[7:16]

    def test_range_ignoring_server_slices_the_200(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, ignore_range=True
        ) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            assert src.read_at(1000, 2000) == blob[1000:3000]

    def test_source_id_excludes_query_and_pins_generation(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            a = HttpSource(stub.url_for("a.bin") + "?sig=AAA")
            b = HttpSource(stub.url_for("a.bin") + "?sig=BBB")
            assert a.source_id == b.source_id
            assert "sig=" not in a.source_id
            size, etag = a.generation()
            assert size == len(blob) and etag

    def test_open_source_url_coercion_and_policy(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src, owns = open_source(stub.url_for("a.bin"))
            assert isinstance(src, HttpSource) and owns
            prev = configure_resilience(
                ResilienceConfig(retry=True, retry_kw={"attempts": 2})
            )
            try:
                wrapped, owns = open_source(stub.url_for("a.bin"))
                assert isinstance(wrapped, RetryingSource)
                assert isinstance(wrapped.inner, HttpSource)
                assert wrapped.generation() == src.generation()
            finally:
                configure_resilience(prev)


class TestMultiRange:
    """read_ranges coalesces N ranges into ONE `Range: bytes=a-b,c-d`
    round trip (multipart/byteranges), with per-range fallback pinned for
    servers that collapse or reject the set."""

    SPANS = [(0, 128), (50_000, 256), (9, 0), (130_000, 64)]

    def _expected(self, blob):
        return [blob[o : o + n] for o, n in self.SPANS]

    def test_one_round_trip_byte_identical(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            reqs = stub.requests
            s0 = metrics.snapshot()
            got = src.read_ranges(self.SPANS)
            assert [bytes(b) for b in got] == self._expected(blob)
            # THE pin: every range in one request (the zero-length range
            # rides for free — it never reaches the wire)
            assert stub.requests == reqs + 1
            assert stub.multirange_requests == 1
            d = metrics.delta(s0)
            assert d.get('io_multirange_requests_total{outcome="ok"}') == 1
            assert d.get("io_multirange_parts_total") == 3

    def test_rejecting_server_latches_per_range_fallback(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, reject_multirange=True
        ) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            s0 = metrics.snapshot()
            got = src.read_ranges(self.SPANS)
            assert [bytes(b) for b in got] == self._expected(blob)
            assert src._multirange is False  # latched for good
            d = metrics.delta(s0)
            assert (
                d.get('io_multirange_requests_total{outcome="unsupported"}')
                == 1
            )
            # the latch holds: the next call goes straight to per-range
            reqs = stub.requests
            got = src.read_ranges(self.SPANS[:2])
            assert [bytes(b) for b in got] == self._expected(blob)[:2]
            assert stub.requests == reqs + 2

    def test_range_ignoring_server_slices_the_full_body(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, ignore_range=True
        ) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            s0 = metrics.snapshot()
            got = src.read_ranges(self.SPANS)
            assert [bytes(b) for b in got] == self._expected(blob)
            d = metrics.delta(s0)
            assert (
                d.get('io_multirange_requests_total{outcome="full_body"}')
                == 1
            )
            # a 200 is the server's choice, not an incapability: the
            # multipart attempt is NOT latched off
            assert src._multirange is True

    def test_single_range_skips_the_multipart_path(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            src.read_ranges([(10, 20)])
            assert stub.multirange_requests == 0

    def test_past_eof_is_typed_without_a_round_trip(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            src = HttpSource(stub.url_for("a.bin"))
            reqs = stub.requests
            with pytest.raises(SourceError):
                src.read_ranges([(0, 16), (len(blob) - 4, 64)])
            assert stub.requests == reqs

    def test_reader_over_multirange_stub_byte_identical(self, corpus):
        data, table = corpus
        with RangeHttpStub(files={"c.parquet": data}) as stub:
            # the projection skips the wide middle column, so each row
            # group needs two non-adjacent runs — the multi-range shape
            with FileReader(
                stub.url_for("c.parquet"), columns=["id", "tag"]
            ) as r:
                ids = [row["id"] for row in r.iter_rows()]
            assert ids == table["id"].to_pylist()
            # the coalesced path actually ran for this scan
            assert stub.multirange_requests >= 1


class TestObjectStoreSource:
    def test_reads_and_initial_sign(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            signs = []

            def sign():
                signs.append(1)
                return stub.url_for("a.bin") + f"?token=T{len(signs)}"

            src = ObjectStoreSource(sign)
            assert src.read_at(5, 10) == blob[5:15]
            assert len(signs) == 1
            assert "token=" not in src.source_id

    def test_proactive_resign_before_expiry(self, blob):
        with RangeHttpStub(files={"a.bin": blob}) as stub:
            now = [1000.0]
            signs = []

            def sign():
                signs.append(1)
                return (
                    stub.url_for("a.bin") + f"?token=T{len(signs)}",
                    now[0] + 100.0,  # valid 100s from "now"
                )

            src = ObjectStoreSource(
                sign, refresh_margin_s=30.0, clock=lambda: now[0]
            )
            src.read_at(0, 16)
            assert len(signs) == 1
            now[0] += 60.0  # still inside validity minus margin
            src.read_at(0, 16)
            assert len(signs) == 1
            now[0] += 15.0  # now inside the refresh margin
            src.read_at(0, 16)
            assert len(signs) == 2

    def test_reactive_resign_on_403(self, blob):
        with RangeHttpStub(
            files={"a.bin": blob}, require_token="T2"
        ) as stub:
            stub.require_token = "T1"  # the first signature is valid...
            signs = []

            def sign():
                signs.append(1)
                return stub.url_for("a.bin") + f"?token=T{len(signs)}"

            src = ObjectStoreSource(sign)
            assert src.read_at(0, 16) == blob[:16]
            stub.require_token = "T2"  # ...until the store rotates
            s0 = metrics.snapshot()
            assert src.read_at(16, 16) == blob[16:32]
            assert len(signs) == 2
            assert metrics.delta(s0).get("io_resigns_total", 0) == 1
            # a 403 that re-signing cannot fix stays a typed error
            stub.require_token = "NEVER"
            with pytest.raises(SourceError) as ei:
                src.read_at(0, 8)
            assert ei.value.code == "http_403"


class TestReaderIntegration:
    def test_filereader_over_url_byte_identical(self, corpus):
        data, table = corpus
        with RangeHttpStub(files={"c.parquet": data}) as stub:
            with FileReader(stub.url_for("c.parquet")) as r:
                assert r.num_rows == table.num_rows
                remote = r.to_arrow()
            # and identical to the SAME reader over local bytes (string
            # width/chunking cosmetics stay identical between the two)
            with FileReader(_stdio.BytesIO(data)) as r:
                local = r.to_arrow()
            assert remote.equals(local)
            assert remote.to_pydict() == table.to_pydict()

    def test_warm_tiered_scan_reads_zero_source_bytes(self, corpus):
        """THE acceptance pin: cold scan populates footer cache + tiered
        block cache; the warm scan's io_bytes_read_total delta is ZERO."""
        data, table = corpus
        with RangeHttpStub(files={"c.parquet": data}) as stub:
            url = stub.url_for("c.parquet")
            fc = FooterCache()
            with TieredCache(
                ram_bytes=1 << 20, disk_bytes=32 << 20
            ) as tc:
                with FileReader(
                    url, footer_cache=fc, block_cache=tc,
                    coalesce_gap="auto",
                ) as r:
                    cold = r.to_arrow()
                s0 = metrics.snapshot()
                with FileReader(
                    url, footer_cache=fc, block_cache=tc,
                    coalesce_gap="auto",
                ) as r:
                    warm = r.to_arrow()
                d = metrics.delta(s0)
                assert d.get("io_bytes_read_total", 0) == 0
                assert warm.equals(cold)
                assert cold.to_pydict() == table.to_pydict()
                # the RAM tier is smaller than the corpus: the warm scan
                # was served by BOTH tiers
                assert d.get('cache_tier_hits_total{tier="ram"}', 0) > 0

    def test_warm_scan_zero_reads_even_through_disk_tier_only(self, corpus):
        data, _ = corpus
        with RangeHttpStub(files={"c.parquet": data}) as stub:
            url = stub.url_for("c.parquet")
            fc = FooterCache()
            # RAM tier far smaller than any chunk -> everything lives on
            # disk; the warm scan must STILL read zero source bytes
            with TieredCache(
                ram_bytes=1 << 20, disk_bytes=32 << 20
            ) as tc:
                with FileReader(url, footer_cache=fc, block_cache=tc) as r:
                    for g in range(r.num_row_groups):
                        r.read_row_group(g)
                s0 = metrics.snapshot()
                with FileReader(url, footer_cache=fc, block_cache=tc) as r:
                    for g in range(r.num_row_groups):
                        r.read_row_group(g)
                assert metrics.delta(s0).get("io_bytes_read_total", 0) == 0

    def test_dataset_over_urls(self, corpus):
        data, table = corpus
        with RangeHttpStub(
            files={"s0.parquet": data, "s1.parquet": data}
        ) as stub:
            ds = ParquetDataset(
                [stub.url_for("s0.parquet"), stub.url_for("s1.parquet")],
                batch_size=10_000,
                columns=["id"],
                cache_bytes=2 << 20,
                cache_disk_bytes=32 << 20,
                io_autotune=True,
            )
            with ds:
                rows = sum(b[("id",)].shape[0] for b in ds)
            assert rows == 2 * table.num_rows


def _read_all_via(source_ctor, n):
    """Read [0, n) in 8 KiB strides through a fresh source; returns bytes
    (or raises)."""
    src = source_ctor()
    try:
        parts = []
        for off in range(0, n, 8192):
            parts.append(src.read_at(off, min(8192, n - off)))
        return b"".join(parts)
    finally:
        src.close()


class TestChaosSweep:
    """Seeded fault sweep: every read of a faulty remote is either
    byte-identical to the local source or a TYPED SourceError — never a
    hang, never torn bytes. The fast subset rides tier-1; the extended
    seed matrix runs under `slow`."""

    FAST = [
        (1, {"error_rate": 0.3}),
        (2, {"short_rate": 0.3}),
        (3, {"error_rate": 0.2, "drop_rate": 0.2, "short_rate": 0.2}),
    ]
    SLOW = [
        (seed, faults)
        for seed in (7, 11, 13, 17)
        for faults in (
            {"error_rate": 0.4},
            {"drop_rate": 0.4},
            {"short_rate": 0.5},
            {"error_rate": 0.25, "drop_rate": 0.15, "short_rate": 0.25},
            {"permanent": True},
        )
    ]

    def _sweep_one(self, blob, seed, faults):
        with RangeHttpStub(files={"a.bin": blob}, seed=seed, **faults) as stub:
            # stat must survive the fault storm to build the source at all
            stub_faults = {k: getattr(stub, k) for k in faults}
            for k in faults:
                setattr(stub, k, 0.0 if k != "permanent" else False)
            base = HttpSource(stub.url_for("a.bin"))
            for k, v in stub_faults.items():
                setattr(stub, k, v)
            ladder = RetryingSource(
                base, attempts=6, sleep=NOSLEEP, seed=seed
            )
            try:
                got = _read_all_via(lambda: ladder, len(blob))
            except SourceError as e:
                # typed, and terminal errors carry their code
                assert isinstance(e, SourceError)
                return "typed"
            assert got == blob
            return "identical"

    @pytest.mark.parametrize("seed,faults", FAST)
    def test_fast_subset(self, blob, seed, faults):
        assert self._sweep_one(blob, seed, faults) in ("typed", "identical")

    @pytest.mark.slow
    @pytest.mark.parametrize("seed,faults", SLOW)
    def test_extended_sweep(self, blob, seed, faults):
        verdict = self._sweep_one(blob, seed, faults)
        if faults.get("permanent"):
            assert verdict == "typed"
        else:
            assert verdict in ("typed", "identical")


class TestSharedTieredCacheDaemonPlusDataset:
    def test_concurrent_sharing_stays_byte_identical(self, corpus, tmp_path):
        """The issue's sharing pin: one TieredCache under a live daemon
        AND a dataset iterating concurrently — responses and batches both
        byte-identical to their solo runs."""
        import pyarrow.parquet as pq

        from parquet_tpu.serve import ScanServer, ServeConfig

        data, table = corpus
        root = tmp_path / "root"
        root.mkdir()
        (root / "c.parquet").write_bytes(data)

        with TieredCache(
            ram_bytes=256 << 10, disk_bytes=32 << 20,
            cache_dir=str(tmp_path / "tier"),
        ) as shared:
            server = ScanServer(
                ServeConfig(port=0, root=str(root), block_cache=shared)
            )
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            try:
                expected_ids = table.column("id").to_pylist()
                results = {}
                errors = []

                def hit_daemon(k):
                    try:
                        body = json.dumps(
                            {"paths": ["c.parquet"], "columns": ["id"]}
                        ).encode()
                        req = urllib.request.Request(
                            server.url + "/v1/scan", data=body,
                            headers={"Content-Type": "application/json"},
                        )
                        with urllib.request.urlopen(req, timeout=60) as resp:
                            rows = [
                                json.loads(line)["id"]
                                for line in resp.read().splitlines()
                                if line
                            ]
                        results[f"daemon{k}"] = rows
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

                def run_dataset(k):
                    try:
                        ds = ParquetDataset(
                            [str(root / "c.parquet")],
                            batch_size=8192, columns=["id"],
                            block_cache=shared, remainder="keep",
                        )
                        with ds:
                            got = np.concatenate(
                                [b[("id",)] for b in ds]
                            ).tolist()
                        results[f"dataset{k}"] = got
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

                threads = [
                    threading.Thread(target=hit_daemon, args=(i,))
                    for i in range(2)
                ] + [
                    threading.Thread(target=run_dataset, args=(i,))
                    for i in range(2)
                ]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join(timeout=120)
                assert not errors, errors
                for name, rows in results.items():
                    assert rows == expected_ids, name
                # tier stats ride /v1/debug/vars (the operator surface)
                with urllib.request.urlopen(
                    server.url + "/v1/debug/vars", timeout=30
                ) as resp:
                    dv = json.loads(resp.read())
                assert dv["cache"]["ram"]["capacity_bytes"] == 256 << 10
                assert "disk" in dv["cache"] and "io_autotune" in dv
            finally:
                server.close()
            # the shared cache survives the daemon's close (caller-owned)
            assert shared.stats()["blocks"] > 0

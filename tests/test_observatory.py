"""The performance observatory's contracts: per-tenant cost accounting,
the live profiler and config endpoints on the serve daemon, the
metric→trace exemplar link, and the persistent bench trend store.

Pinned here:
  * CostLedger bounds and arithmetic (overflow bucket, unit_clock CPU
    attribution through the contextvar, trace-rollup byte charges);
  * a 3-tenant concurrent hammer whose per-tenant CPU/byte totals
    reconcile with process-level counters, with label cardinality held
    under adversarial X-Tenant values;
  * GET /v1/debug/tenants, /v1/debug/vars, /v1/debug/profile (collapsed/
    top/json + typed 400/409s) on a live daemon;
  * the OpenMetrics exemplar on serve_request_seconds carrying a
    request-id that resolves in the flight recorder — dashboard spike →
    exact trace, the full loop;
  * `bench.py --record` / `--trend` / one-arg `--compare` round-tripping
    artifacts through BENCH_history.jsonl, including the schema check
    `make check` leans on;
  * `parquet-tool debug --vars/--tenants` and `profile --live`.
"""

from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.obs.cost import (
    CostLedger,
    charge_request_from_trace,
    cost_context,
    unit_clock,
)
from parquet_tpu.serve import ScanServer, ServeConfig
from parquet_tpu.tools.parquet_tool import main as tool_main
from parquet_tpu.utils import metrics
from parquet_tpu.utils.trace import add_bytes, bump, decode_trace, stage

WATCHDOG_S = 30.0
BENCH = str(Path(__file__).resolve().parent.parent / "bench.py")

ROWS = 3000
ROW_GROUP = 1000


# -- fixtures ------------------------------------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("obsy_corpus")
    rng = np.random.default_rng(5)
    t = pa.table(
        {
            "id": pa.array(np.arange(ROWS, dtype=np.int64)),
            "v": pa.array(rng.standard_normal(ROWS).astype(np.float64)),
            "name": pa.array([f"n{i % 13}" for i in range(ROWS)]),
        }
    )
    pq.write_table(t, str(d / "a.parquet"), row_group_size=ROW_GROUP)
    return d


@pytest.fixture()
def server(corpus):
    with ScanServer(ServeConfig(port=0, root=str(corpus), cache_mb=16)) as s:
        s.start_background()
        s.service.ledger.reset()  # per-test ledger isolation
        yield s


def _request(server, method, path, body=None, headers=None, timeout=WATCHDOG_S):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=timeout)
    try:
        conn.request(
            method,
            path,
            body=json.dumps(body).encode() if body is not None else None,
            headers=headers or {},
        )
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _scan(server, tenant, request_id=None):
    headers = {"X-Tenant": tenant}
    if request_id is not None:
        headers["X-Request-Id"] = request_id
    status, hdrs, body = _request(
        server, "POST", "/v1/scan", {"paths": ["*.parquet"]}, headers
    )
    assert status == 200, body[:200]
    return hdrs, body


# -- the cost ledger -----------------------------------------------------------


class TestCostLedger:
    def test_charges_accumulate_and_table_sorts_by_cpu(self):
        led = CostLedger()
        led.charge_cpu("b", 0.2)
        led.charge_cpu("a", 0.5)
        led.charge_request("a", decoded_bytes=100, payload_bytes=10)
        rows = led.table()
        assert [r["tenant"] for r in rows] == ["a", "b"]
        assert rows[0]["cpu_seconds"] == pytest.approx(0.5)
        assert rows[0]["decoded_bytes"] == 100 and rows[0]["requests"] == 1
        totals = led.totals()
        assert totals["cpu_seconds"] == pytest.approx(0.7)
        assert totals["units"] == 2

    def test_bounded_tenants_collapse_to_overflow(self):
        led = CostLedger(max_tenants=2)
        for name in ("t1", "t2", "hostile3", "hostile4", "hostile5"):
            led.charge_cpu(name, 0.01)
        rows = led.table()
        names = {r["tenant"] for r in rows}
        assert names == {"t1", "t2", "__overflow__"}
        # nothing was dropped: totals stay exact
        assert led.totals()["cpu_seconds"] == pytest.approx(0.05)

    def test_hostile_keys_truncate(self):
        led = CostLedger()
        led.charge_cpu("x" * 500, 0.01)
        [row] = led.table()
        assert len(row["tenant"]) == 64

    def test_metric_families_ride_charges(self):
        reg = metrics.MetricsRegistry()
        led = CostLedger(registry=reg)
        led.charge_cpu("alice", 0.25)
        led.charge_request("alice", decoded_bytes=1234)
        assert reg.get(
            "serve_tenant_cpu_seconds_total", tenant="alice"
        ) == pytest.approx(0.25)
        assert reg.get(
            "serve_tenant_decoded_bytes_total", tenant="alice"
        ) == 1234

    def test_unit_clock_charges_context_tenant_cpu(self):
        led = CostLedger(registry=metrics.MetricsRegistry())
        with cost_context("carol"):
            with unit_clock(ledger=led):
                # real CPU, not sleep: thread_time only counts cycles
                x = 0
                for i in range(400_000):
                    x += i
        [row] = led.table()
        assert row["tenant"] == "carol"
        assert row["cpu_seconds"] > 0 and row["units"] == 1

    def test_unit_clock_outside_context_charges_nothing(self):
        led = CostLedger(registry=metrics.MetricsRegistry())
        with unit_clock(ledger=led):
            pass
        assert led.table() == []

    def test_charge_request_from_trace_reads_rollup(self):
        led = CostLedger(registry=metrics.MetricsRegistry())
        with decode_trace() as t:
            with stage("decode"):
                add_bytes("decode.bytes", 5000)
            with stage("io.read", nbytes=0):
                add_bytes("io.read", 800)
            bump("io_cache_hit")
            bump("io_cache_hit")
            bump("io_cache_miss")
        charge_request_from_trace("dave", t, nbytes=42, ledger=led)
        [row] = led.table()
        assert row["decoded_bytes"] == 5000
        assert row["source_bytes"] == 800
        assert row["payload_bytes"] == 42
        assert row["cache_hits"] == 2 and row["cache_misses"] == 1
        assert row["requests"] == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            CostLedger(max_tenants=0)


# -- the daemon's cost/debug endpoints -----------------------------------------


class TestTenantAccounting:
    def test_three_tenant_hammer_reconciles(self, server):
        """The acceptance pin: under a 3-tenant concurrent hammer the
        per-tenant CPU/byte attributions sum to the process totals
        within tolerance, and equal work bills equally."""
        snap0 = metrics.snapshot()
        cpu0 = time.process_time()
        per_tenant = 3
        errors = []

        def hammer(tenant):
            try:
                for _ in range(per_tenant):
                    _scan(server, tenant)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(t,))
            for t in ("alice", "bob", "carol")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(WATCHDOG_S)
        assert not errors, errors
        cpu_delta = time.process_time() - cpu0
        mdelta = metrics.delta(snap0)

        status, _, body = _request(server, "GET", "/v1/debug/tenants")
        assert status == 200
        doc = json.loads(body)
        rows = {r["tenant"]: r for r in doc["tenants"]}
        assert set(rows) >= {"alice", "bob", "carol"}
        for name in ("alice", "bob", "carol"):
            r = rows[name]
            assert r["requests"] == per_tenant
            assert r["cpu_seconds"] > 0
            assert r["decoded_bytes"] > 0
            assert r["payload_bytes"] > 0
            assert r["units"] == per_tenant * (ROWS // ROW_GROUP)
        # equal work bills equal bytes, exactly
        assert (
            rows["alice"]["decoded_bytes"]
            == rows["bob"]["decoded_bytes"]
            == rows["carol"]["decoded_bytes"]
        )
        totals = doc["totals"]
        # CPU: the tenants' sum can never exceed what the process spent,
        # and executor units must be a meaningful share of it
        assert totals["cpu_seconds"] <= cpu_delta + 0.25
        assert totals["cpu_seconds"] > 0
        # decoded bytes reconcile with the process counter: the ledger is
        # fed from the SAME choke point (decompress_block mirrors its
        # output bytes into each request's trace), so the tenant sum
        # equals the bytes_uncompressed_total delta
        uncompressed = sum(
            v
            for k, v in mdelta.items()
            if k.startswith("bytes_uncompressed_total")
        )
        assert uncompressed > 0
        assert totals["decoded_bytes"] == pytest.approx(uncompressed, rel=0.02)
        # and the always-on families carry the same story
        for name in ("alice", "bob", "carol"):
            assert (
                metrics.get("serve_tenant_cpu_seconds_total", tenant=name) > 0
            )
            assert (
                metrics.get("serve_tenant_decoded_bytes_total", tenant=name)
                == rows[name]["decoded_bytes"]
            )

    def test_adversarial_tenant_values_stay_bounded(self, server):
        """Hostile X-Tenant headers: truncated to the admission key form,
        label-escaped in the exposition, and the daemon stays typed."""
        # (a raw \n in a header value is refused by http.client itself —
        # it cannot even reach the daemon; a tab is legal in Prometheus
        # label values but another suite regex-pins whitespace-free
        # samples on the process registry, so stress braces instead)
        hostile = ["x" * 500, 'evil"quote', 'evil{inj="1"}', "  "]
        for h in hostile:
            _scan(server, h)
        status, _, body = _request(server, "GET", "/v1/debug/tenants")
        doc = json.loads(body)
        for r in doc["tenants"]:
            assert len(r["tenant"]) <= 64
        # the whitespace-only header collapsed to the default key
        assert "default" in {r["tenant"] for r in doc["tenants"]}
        status, _, text = _request(server, "GET", "/metrics")
        assert status == 200
        exposition = text.decode()
        for line in exposition.splitlines():
            assert "\n" not in line  # trivially true: the split is the pin
        # the quote arrived escaped, never raw
        assert 'evil\\"quote' in exposition

    def test_debug_vars_snapshot(self, server):
        status, _, body = _request(server, "GET", "/v1/debug/vars")
        assert status == 200
        doc = json.loads(body)
        assert doc["pid"] == os.getpid()
        assert doc["uptime_s"] >= 0
        assert doc["version"]
        assert doc["serve"]["max_inflight"] == 32
        assert doc["serve"]["cache_mb"] == 16
        assert doc["obs"]["debug_ring_size"] > 0
        assert set(doc["resilience"]) == {"breaker", "retry", "hedge"}
        assert "depths" in doc["pools"]
        # the uptime gauge rides the registry for scrapers too
        status, _, text = _request(server, "GET", "/metrics")
        assert "parquet_tpu_process_uptime_seconds" in text.decode()


class TestLiveProfile:
    def test_profile_attributes_serve_lanes_under_load(self, server):
        """The acceptance pin: a live profile window on a serving daemon
        returns a non-empty collapsed profile attributing samples to the
        named pqt-* lanes."""
        stop = threading.Event()
        errors = []

        def hammer():
            while not stop.is_set():
                try:
                    _scan(server, "prof")
                except Exception as e:  # pragma: no cover
                    if not stop.is_set():
                        errors.append(e)
                    return

        th = threading.Thread(target=hammer)
        th.start()
        try:
            status, hdrs, body = _request(
                server,
                "GET",
                "/v1/debug/profile?seconds=0.8&interval_ms=5",
                timeout=WATCHDOG_S,
            )
        finally:
            stop.set()
            th.join(WATCHDOG_S)
        assert not errors, errors
        assert status == 200
        text = body.decode()
        assert text.strip(), "empty collapsed profile"
        lanes = {line.split(";", 1)[0] for line in text.splitlines()}
        assert any(lane.startswith("pqt-") for lane in lanes), lanes
        # every line is collapsed-stack shaped: frames then a count
        for line in text.splitlines():
            stack, _, count = line.rpartition(" ")
            assert count.isdigit() and ";" in stack

    def test_profile_top_and_json_formats(self, server):
        status, _, body = _request(
            server, "GET", "/v1/debug/profile?seconds=0.2&format=top"
        )
        assert status == 200
        assert body.decode().startswith("profile:")
        status, _, body = _request(
            server, "GET", "/v1/debug/profile?seconds=0.2&format=json"
        )
        assert status == 200
        doc = json.loads(body)
        assert {"samples", "lanes", "stacks", "interval_s"} <= set(doc)

    @pytest.mark.parametrize(
        "qs",
        [
            "seconds=0",
            "seconds=61",
            "seconds=nope",
            "seconds=1&interval_ms=0.1",
            "seconds=1&format=svg",
        ],
    )
    def test_bad_params_are_typed_400s(self, server, qs):
        status, _, body = _request(
            server, "GET", f"/v1/debug/profile?{qs}"
        )
        assert status == 400
        assert json.loads(body)["error"]["code"] == "bad_request"

    def test_concurrent_window_is_typed_409(self, server):
        results = {}

        def long_window():
            results["first"] = _request(
                server, "GET", "/v1/debug/profile?seconds=1.5"
            )

        th = threading.Thread(target=long_window)
        th.start()
        time.sleep(0.3)  # let the first window take the capture lock
        status, _, body = _request(
            server, "GET", "/v1/debug/profile?seconds=0.2"
        )
        th.join(WATCHDOG_S)
        assert results["first"][0] == 200
        assert status == 409
        assert json.loads(body)["error"]["code"] == "profile_in_progress"


class TestExemplarLoop:
    def test_latency_bucket_names_a_fetchable_request(self, server):
        """The metric→trace link end to end: scan with a known id, then
        the OpenMetrics exposition's serve_request_seconds bucket carries
        that id as an exemplar, and the id resolves in the flight
        recorder."""
        rid = "exemplar-loop-1"
        _scan(server, "alice", request_id=rid)
        status, hdrs, body = _request(
            server,
            "GET",
            "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        assert status == 200
        assert "application/openmetrics-text" in hdrs["Content-Type"]
        om = body.decode()
        assert om.rstrip().endswith("# EOF")
        ex_lines = [
            ln
            for ln in om.splitlines()
            if "serve_request_seconds_bucket" in ln and " # {" in ln
        ]
        assert ex_lines, "no exemplar on serve_request_seconds"
        ids = {
            ln.split('request_id="', 1)[1].split('"', 1)[0]
            for ln in ex_lines
            if 'request_id="' in ln
        }
        assert rid in ids
        # the loop closes: the id the dashboard shows fetches the record
        status, _, body = _request(
            server, "GET", f"/v1/debug/requests/{rid}"
        )
        assert status == 200
        rec = json.loads(body)
        assert rec["id"] == rid and rec["status"] == 200
        # ... and the record's stage rollup is exclusive: inner decode
        # stages under serve.execute carry their nested share
        stages = rec["stages"]
        assert "serve.execute" in stages
        assert "nested_seconds" not in stages["serve.execute"]
        assert any(
            "nested_seconds" in s
            for name, s in stages.items()
            if name != "serve.execute"
        )

    def test_classic_scrape_unchanged(self, server):
        _scan(server, "alice")
        status, hdrs, body = _request(server, "GET", "/metrics")
        assert status == 200
        assert hdrs["Content-Type"].startswith("text/plain")
        text = body.decode()
        assert "# EOF" not in text and " # {" not in text


# -- the CLI surfaces ----------------------------------------------------------


class TestDebugCLI:
    def test_debug_vars_and_tenants(self, server, capsys):
        _scan(server, "alice")
        assert tool_main(["debug", server.url, "--vars"]) == 0
        out = capsys.readouterr().out
        doc = json.loads(out)
        assert doc["pid"] == os.getpid()
        assert tool_main(["debug", server.url, "--tenants"]) == 0
        out = capsys.readouterr().out
        assert "TENANT" in out and "alice" in out and "TOTAL" in out

    def test_profile_live(self, server, capsys, tmp_path):
        assert (
            tool_main(
                ["profile", "--live", server.url, "--seconds", "0.2"]
            )
            == 0
        )
        out = capsys.readouterr().out
        assert out.strip()
        assert all(" " in ln for ln in out.strip().splitlines())
        outfile = tmp_path / "collapsed.txt"
        assert (
            tool_main(
                [
                    "profile",
                    "--live",
                    server.url,
                    "--seconds",
                    "0.2",
                    "--top",
                    "-o",
                    str(outfile),
                ]
            )
            == 0
        )
        assert outfile.read_text().startswith("profile:")

    def test_profile_file_mode_still_requires_args(self, capsys):
        assert tool_main(["profile"]) == 2

    def test_profile_cross_mode_flags_are_refused(self, server, capsys):
        # live-only flags in file mode: refused, not silently dropped
        assert tool_main(["profile", "f.parquet", "-o", "t.json", "--top"]) == 2
        assert "--live mode only" in capsys.readouterr().err
        # file-mode flags against a daemon: refused too
        rc = tool_main(
            ["profile", "--live", server.url, "--columns", "a,b"]
        )
        assert rc == 2
        assert "file mode" in capsys.readouterr().err

    def test_profile_live_unreachable_is_typed(self, capsys):
        rc = tool_main(
            ["profile", "--live", "http://127.0.0.1:9", "--seconds", "0.1"]
        )
        assert rc == 1
        assert "cannot reach" in capsys.readouterr().err


# -- the bench trend store -----------------------------------------------------


def _bench(*args, cwd):
    return subprocess.run(
        [sys.executable, BENCH, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        cwd=str(cwd),
        timeout=120,
    )


class TestBenchTrendStore:
    def _artifact(self, tmp_path, name, value, rps):
        art = {
            "value": value,
            "unit": "rows/s",
            "serve": {"concurrency_sweep": {"16": {"rps": rps, "p99_ms": 100}}},
        }
        p = tmp_path / name
        p.write_text(json.dumps(art))
        return p

    def test_record_trend_compare_round_trip(self, tmp_path):
        hist = tmp_path / "hist.jsonl"
        a1 = self._artifact(tmp_path, "a1.json", 100.0, 5.0)
        a2 = self._artifact(tmp_path, "a2.json", 104.0, 5.2)
        r = _bench(
            "--record", str(a1), "--label", "r06", "--history", str(hist),
            cwd=tmp_path,
        )
        assert r.returncode == 0, r.stdout
        out = r.stdout.decode()
        assert "r06" in out and "tracked" in out
        # provenance rides every entry
        [entry] = [
            json.loads(ln) for ln in hist.read_text().splitlines() if ln
        ]
        assert entry["label"] == "r06"
        assert entry["git_rev"] and entry["config"]
        assert entry["artifact"]["value"] == 100.0
        r = _bench("--record", str(a2), "--history", str(hist), cwd=tmp_path)
        assert r.returncode == 0
        # the label-less record CONTINUES the rNN sequence past the
        # seeded round instead of restarting at r02 and colliding later
        labels = [
            json.loads(ln)["label"]
            for ln in hist.read_text().splitlines()
            if ln
        ]
        assert labels == ["r06", "r07"]
        # trend renders both rounds with the last-vs-first ratio
        r = _bench("--trend", "--history", str(hist), cwd=tmp_path)
        assert r.returncode == 0, r.stdout
        out = r.stdout.decode()
        assert "2 rounds" in out
        assert "value" in out and "x1.040" in out
        # one-arg compare defaults to the LATEST recorded round
        r = _bench("--compare", str(a2), "--history", str(hist), cwd=tmp_path)
        assert r.returncode == 0, r.stdout
        assert "no tracked regressions" in r.stdout.decode()
        # a regressing artifact fails the same one-arg gate
        bad = self._artifact(tmp_path, "bad.json", 80.0, 4.0)
        r = _bench("--compare", str(bad), "--history", str(hist), cwd=tmp_path)
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout.decode()

    def test_record_prefers_run_time_fingerprint(self, tmp_path):
        """An artifact stamped with bench_config at --json time records
        THAT fingerprint, not the env of the --record shell."""
        hist = tmp_path / "hist.jsonl"
        art = {
            "value": 1.0,
            "bench_config": {"fingerprint": "cafe0123beef", "basis": {}},
        }
        p = tmp_path / "a.json"
        p.write_text(json.dumps(art))
        assert (
            _bench(
                "--record", str(p), "--history", str(hist), cwd=tmp_path
            ).returncode
            == 0
        )
        [entry] = [json.loads(ln) for ln in hist.read_text().splitlines() if ln]
        assert entry["config"] == "cafe0123beef"

    def test_json_artifact_carries_run_config(self, tmp_path):
        """Artifacts written via --json embed the run-time config
        fingerprint (what --record prefers over its own shell's env)."""
        out = tmp_path / "stamped.json"
        sys.path.insert(0, str(Path(BENCH).parent))
        try:
            import bench as bench_mod
        finally:
            sys.path.pop(0)
        old = bench_mod._JSON_OUT
        bench_mod._JSON_OUT = str(out)
        try:
            bench_mod._write_artifact({"value": 2.0})
        finally:
            bench_mod._JSON_OUT = old
        doc = json.loads(out.read_text())
        assert doc["bench_config"]["fingerprint"]
        assert doc["value"] == 2.0

    def test_duplicate_label_refused(self, tmp_path):
        hist = tmp_path / "hist.jsonl"
        a1 = self._artifact(tmp_path, "a1.json", 1.0, 1.0)
        assert (
            _bench(
                "--record", str(a1), "--label", "rX", "--history", str(hist),
                cwd=tmp_path,
            ).returncode
            == 0
        )
        r = _bench(
            "--record", str(a1), "--label", "rX", "--history", str(hist),
            cwd=tmp_path,
        )
        assert r.returncode != 0
        assert "already recorded" in r.stdout.decode()

    def test_trend_schema_check_rejects_malformed_store(self, tmp_path):
        hist = tmp_path / "hist.jsonl"
        hist.write_text('{"label": "r01"}\n')  # missing provenance keys
        r = _bench("--trend", "--history", str(hist), cwd=tmp_path)
        assert r.returncode != 0
        assert "missing" in r.stdout.decode()
        hist.write_text("not json\n")
        r = _bench("--trend", "--history", str(hist), cwd=tmp_path)
        assert r.returncode != 0

    def test_compare_one_arg_without_history_is_typed(self, tmp_path):
        a1 = self._artifact(tmp_path, "a1.json", 1.0, 1.0)
        r = _bench(
            "--compare", str(a1), "--history", str(tmp_path / "none.jsonl"),
            cwd=tmp_path,
        )
        assert r.returncode != 0
        assert "no trend store" in r.stdout.decode()

    def test_committed_history_round_trips(self):
        """The repo's own trend store (seeded with BENCH_r06 this PR)
        parses, trends, and one-arg-compares against its latest round."""
        repo = Path(BENCH).parent
        hist = repo / "BENCH_history.jsonl"
        assert hist.exists(), "BENCH_history.jsonl missing from the repo"
        r = _bench("--trend", cwd=repo)
        assert r.returncode == 0, r.stdout
        assert "rounds in" in r.stdout.decode()

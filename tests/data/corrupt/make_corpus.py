"""Regenerate the hand-crafted corrupt-file corpus (committed alongside).

Every file derives deterministically from one pristine base written by OUR
FileWriter (seeded values, with_crc=True, snappy) so the corpus does not
depend on the installed pyarrow's byte output. Each mutation targets one
failure family of the decode ladder; tests/test_faults.py asserts every file
raises a typed Parquet error on both the staged and the fused read path.

    python tests/data/corrupt/make_corpus.py   # rewrites the corpus in place
"""

from __future__ import annotations

import io
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(HERE, "..", "..", "..")))


def build_base() -> bytes:
    import numpy as np

    from parquet_tpu.core.writer import FileWriter
    from parquet_tpu.meta.parquet_types import Type
    from parquet_tpu.schema.builder import message, optional, required, string

    rng = np.random.default_rng(2026)
    schema = message(
        required("id", Type.INT64),
        optional("name", string()),
        optional("score", Type.DOUBLE),
    )
    rows = [
        {
            "id": int(i),
            "name": None if i % 11 == 0 else f"name_{i % 23}",
            "score": None if i % 7 == 0 else float(rng.random()),
        }
        for i in range(600)
    ]
    buf = io.BytesIO()
    with FileWriter(buf, schema, codec="snappy", with_crc=True) as w:
        for lo in range(0, len(rows), 200):  # 3 row groups
            w.write_rows(rows[lo : lo + 200])
            w.flush_row_group()
    return buf.getvalue()


def main() -> None:
    from parquet_tpu.testing.faults import _try_patch, map_pages

    base = build_base()
    sites = map_pages(base)
    data_sites = [s for s in sites if s.kind in (0, 3) and s.payload_len > 0]
    out: dict[str, bytes] = {"pristine.parquet": base}

    n = len(base)
    out["truncated_footer.parquet"] = base[: n - 9]  # mid footer-len/magic
    out["truncated_mid_page.parquet"] = base[: data_sites[0].payload_offset + 5]
    out["bad_magic.parquet"] = base[:-4] + b"XXXX"
    out["empty.parquet"] = b""

    s = data_sites[0]
    flipped = bytearray(base)
    flipped[s.payload_offset + s.payload_len // 2] ^= 0x10
    out["crc_mismatch.parquet"] = bytes(flipped)

    garbage = bytearray(base)
    garbage[s.header_offset] = 0xFF  # delta 15 / wire 15: unknown wire type
    out["page_header_garbage.parquet"] = bytes(garbage)

    def bump_nv(h):
        hh = h.data_page_header or h.data_page_header_v2
        hh.num_values += 1

    patched = _try_patch(base, s, bump_nv)
    assert patched is not None, "num_values patch must be length-preserving"
    out["lying_num_values.parquet"] = patched

    def shrink_us(h):
        h.uncompressed_page_size -= 1

    patched = _try_patch(base, s, shrink_us)
    assert patched is not None, "size patch must be length-preserving"
    out["lying_uncompressed_size.parquet"] = patched

    footer_len = int.from_bytes(base[-8:-4], "little")
    fstart = n - 8 - footer_len
    poisoned = bytearray(base)
    poisoned[fstart : fstart + 7] = bytes([0x19, 0xF6]) + b"\xff\xff\xff\xff\x7f"
    out["footer_giant_list.parquet"] = bytes(poisoned)

    for name, blob in out.items():
        with open(os.path.join(HERE, name), "wb") as f:
            f.write(blob)
        print(f"wrote {name} ({len(blob)} bytes)")


if __name__ == "__main__":
    main()

"""Nested-to-Arrow assembly (core/arrow_nested.py) proven against pyarrow.

Every shape the reference reads through its Dremel assembly
(reference schema.go:216-312, floor/reader.go:302-409) must come out of
FileReader.to_arrow equal to pyarrow.parquet.read_table on the same file:
structs, MAPs, multi-level lists, list-of-struct, struct-of-list, legacy
repeated groups and bare repeated leaves — across both decode backends,
with nulls at every nesting depth, plus projection and row-group subsets.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema

BACKENDS = ["host", "tpu_roundtrip"]


def _assert_matches_pyarrow(path, backend, columns=None, row_groups=None):
    want = pq.read_table(path)
    if columns is not None:
        want = want.select(columns)
    with FileReader(path, backend=backend) as r:
        out = r.to_arrow(columns=columns, row_groups=row_groups)
    if row_groups is not None:
        pf = pq.ParquetFile(path)
        pieces = [pf.read_row_group(i) for i in row_groups]
        want = pa.concat_tables(pieces) if pieces else want.slice(0, 0)
        if columns is not None:
            want = want.select(columns)
    assert out.num_rows == want.num_rows
    for c in want.column_names:
        got = out.column(c).to_pylist()
        exp = want.column(c).to_pylist()
        assert got == exp, f"{c}: {got[:5]!r} != {exp[:5]!r}"
    return out


@pytest.mark.parametrize("backend", BACKENDS)
class TestNestedShapes:
    def test_struct_of_list(self, tmp_path, backend):
        t = pa.table({
            "s": pa.array(
                [
                    {"v": [1, 2], "w": "a"},
                    {"v": None, "w": None},
                    None,
                    {"v": [], "w": "d"},
                    {"v": [None, 5], "w": "e"},
                ],
                pa.struct([("v", pa.list_(pa.int64())), ("w", pa.string())]),
            ),
        })
        p = str(tmp_path / "sol.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_list_of_struct(self, tmp_path, backend):
        t = pa.table({
            "ls": pa.array(
                [
                    [{"a": 1, "b": "x"}, {"a": None, "b": None}],
                    [],
                    None,
                    [None, {"a": 4, "b": "q"}],
                ],
                pa.list_(pa.struct([("a", pa.int64()), ("b", pa.string())])),
            ),
        })
        p = str(tmp_path / "los.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_map_with_null_values(self, tmp_path, backend):
        t = pa.table({
            "m": pa.array(
                [
                    [("k1", 1.5), ("k2", None)],
                    [],
                    None,
                    [("k3", 3.0)],
                ],
                pa.map_(pa.string(), pa.float64()),
            ),
        })
        p = str(tmp_path / "mnv.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_three_level_list(self, tmp_path, backend):
        t = pa.table({
            "lll": pa.array(
                [
                    [[[1, None], []], None, [[2]]],
                    None,
                    [],
                    [[]],
                    [[[], [3, 4, 5]]],
                ],
                pa.list_(pa.list_(pa.list_(pa.int32()))),
            ),
        })
        p = str(tmp_path / "l3.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_map_of_list_values(self, tmp_path, backend):
        t = pa.table({
            "ml": pa.array(
                [[("a", [1, 2]), ("b", None)], None, [("c", [])]],
                pa.map_(pa.string(), pa.list_(pa.int64())),
            ),
        })
        p = str(tmp_path / "ml.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_struct_in_struct_mixed_nullability(self, tmp_path, backend):
        inner = pa.struct([("x", pa.int32()), ("y", pa.string())])
        t = pa.table({
            "o": pa.array(
                [
                    {"i": {"x": 1, "y": "a"}, "z": 1.0},
                    {"i": None, "z": None},
                    None,
                    {"i": {"x": None, "y": None}, "z": 4.0},
                ],
                pa.struct([("i", inner), ("z", pa.float64())]),
            ),
        })
        p = str(tmp_path / "ss.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_list_of_struct_of_list(self, tmp_path, backend):
        elem = pa.struct([("tags", pa.list_(pa.string())), ("n", pa.int64())])
        t = pa.table({
            "deep": pa.array(
                [
                    [{"tags": ["a", None], "n": 1}, {"tags": None, "n": None}],
                    None,
                    [],
                    [None],
                    [{"tags": [], "n": 9}],
                ],
                pa.list_(elem),
            ),
        })
        p = str(tmp_path / "lsl.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_all_null_struct_column(self, tmp_path, backend):
        t = pa.table({
            "g": pa.array(
                [None] * 40, pa.struct([("a", pa.int64()), ("b", pa.string())])
            ),
        })
        p = str(tmp_path / "anull.parquet")
        pq.write_table(t, p)
        _assert_matches_pyarrow(p, backend)

    def test_fixed_width_in_nested(self, tmp_path, backend):
        t = pa.table({
            "s": pa.array(
                [{"f": b"abcd"}, None, {"f": None}],
                pa.struct([("f", pa.binary(4))]),
            ),
            "lf": pa.array(
                [[b"pqrs", None], None, [b"wxyz"]], pa.list_(pa.binary(4))
            ),
        })
        p = str(tmp_path / "fx.parquet")
        pq.write_table(t, p, use_dictionary=False)
        _assert_matches_pyarrow(p, backend)

    def test_randomized_multi_row_group(self, tmp_path, backend):
        rng = np.random.default_rng(42)
        n = 4_000

        def maybe_null(p, v):
            return None if rng.random() < p else v

        rows_s = [
            maybe_null(0.1, {
                "v": maybe_null(0.2, [
                    maybe_null(0.15, int(x)) for x in rng.integers(0, 99, int(rng.integers(0, 5)))
                ]),
                "w": maybe_null(0.2, f"s{int(rng.integers(0, 50))}"),
            })
            for _ in range(n)
        ]
        rows_m = [
            maybe_null(0.1, [
                (f"k{j}", maybe_null(0.2, float(j)))
                for j in range(int(rng.integers(0, 4)))
            ])
            for _ in range(n)
        ]
        t = pa.table({
            "s": pa.array(
                rows_s, pa.struct([("v", pa.list_(pa.int64())), ("w", pa.string())])
            ),
            "m": pa.array(rows_m, pa.map_(pa.string(), pa.float64())),
            "flat": pa.array(rng.integers(0, 1 << 40, n), pa.int64()),
        })
        p = str(tmp_path / "rand.parquet")
        pq.write_table(t, p, row_group_size=1_100, compression="snappy")
        _assert_matches_pyarrow(p, backend)
        # row-group subset through the nested path
        _assert_matches_pyarrow(p, backend, row_groups=[1, 3])

    def test_projection_into_struct(self, tmp_path, backend):
        t = pa.table({
            "s": pa.array(
                [{"a": 1, "b": "x", "c": 2.0}, None, {"a": 3, "b": None, "c": None}],
                pa.struct([("a", pa.int64()), ("b", pa.string()), ("c", pa.float64())]),
            ),
            "other": pa.array([1, 2, 3], pa.int32()),
        })
        p = str(tmp_path / "proj.parquet")
        pq.write_table(t, p)
        want = [
            None if r is None else {"a": r["a"], "b": r["b"]}
            for r in t.column("s").to_pylist()
        ]
        with FileReader(p, backend=backend) as r:
            out = r.to_arrow(columns=["s.a", "s.b"])
            empty = r.to_arrow(columns=["s.a", "s.b"], row_groups=[])
        assert out.column_names == ["s"]
        assert out.column("s").to_pylist() == want
        # the zero-group schema prunes the same projected-out member
        assert empty.column("s").type == out.column("s").type

    def test_partial_map_projection(self, tmp_path, backend):
        """Selecting only a MAP's keys (no Arrow MAP without both children)
        degrades to the underlying list-of-struct, consistently across the
        data and zero-group branches."""
        t = pa.table({
            "m": pa.array(
                [[("a", 1.0), ("b", None)], None, []],
                pa.map_(pa.string(), pa.float64()),
            ),
        })
        p = str(tmp_path / "pm.parquet")
        pq.write_table(t, p)
        with FileReader(p, backend=backend) as r:
            out = r.to_arrow(columns=["m.key_value.key"])
            empty = r.to_arrow(columns=["m.key_value.key"], row_groups=[])
        assert out.column("m").to_pylist() == [
            [{"key": "a"}, {"key": "b"}], None, []
        ]
        assert empty.column("m").type == out.column("m").type


@pytest.mark.parametrize("backend", BACKENDS)
class TestLegacyShapes:
    """Non-canonical shapes only our own writer (and old Hadoop writers)
    produce; oracle = pyarrow reading the file we wrote."""

    def test_bare_repeated_leaf(self, tmp_path, backend):
        schema = parse_schema("message m { repeated int32 r; }")
        p = str(tmp_path / "bare.parquet")
        with FileWriter(p, schema) as w:
            w.write_rows([{"r": [1, 2, 3]}, {"r": []}, {"r": [7]}])
        _assert_matches_pyarrow(p, backend)

    def test_bare_repeated_string_leaf(self, tmp_path, backend):
        schema = parse_schema("message m { repeated binary s (UTF8); }")
        p = str(tmp_path / "bares.parquet")
        with FileWriter(p, schema) as w:
            w.write_rows([{"s": ["a", "bb"]}, {"s": []}, {"s": ["ccc"]}])
        _assert_matches_pyarrow(p, backend)

    def test_legacy_repeated_group(self, tmp_path, backend):
        schema = parse_schema(
            "message m { repeated group rec { required int64 id; "
            "optional binary tag (UTF8); } }"
        )
        p = str(tmp_path / "lrg.parquet")
        with FileWriter(p, schema) as w:
            w.write_rows([
                {"rec": [{"id": 1, "tag": "a"}, {"id": 2, "tag": None}]},
                {"rec": []},
                {"rec": [{"id": 3, "tag": "c"}]},
            ])
        _assert_matches_pyarrow(p, backend)

    def test_optional_group_bare_repeated_leaf(self, tmp_path, backend):
        schema = parse_schema(
            "message m { required group a { optional group b "
            "{ repeated int32 c; } } }"
        )
        p = str(tmp_path / "odd.parquet")
        with FileWriter(p, schema) as w:
            w.write_rows([
                {"a": {"b": {"c": [5, 6]}}},
                {"a": {"b": {"c": []}}},
                {"a": {"b": None}},
            ])
        _assert_matches_pyarrow(p, backend)

    def test_roundtrip_own_writer_nested(self, tmp_path, backend):
        """ours -> ours columnar export, checked against pyarrow's read of
        the same bytes (three independent decoders agree)."""
        schema = parse_schema(
            "message m { optional group s (LIST) { repeated group list { "
            "optional group element { required int64 x; "
            "optional binary y (UTF8); } } } }"
        )
        p = str(tmp_path / "own.parquet")
        rows = [
            {"s": [{"x": 1, "y": "a"}, {"x": 2, "y": None}]},
            {"s": None},
            {"s": []},
            {"s": [None]},
        ]
        with FileWriter(p, schema) as w:
            w.write_rows(rows)
        out = _assert_matches_pyarrow(p, backend)
        assert out.column("s").to_pylist() == [
            [{"x": 1, "y": "a"}, {"x": 2, "y": None}],
            None,
            [],
            [None],
        ]


@pytest.mark.parametrize("backend", BACKENDS)
def test_nested_v2_pages_num_nulls_quirk(tmp_path, backend):
    """parquet-cpp's V2 pages count num_nulls as null VALUES only (empty
    lists and null ancestors excluded), so num_values - num_nulls does NOT
    equal the data section's value count for nested columns — the reader
    must trust the levels, not the header claim (found by differential
    fuzz; a strict equality check used to reject valid pyarrow files)."""
    elem = pa.struct([("a", pa.int64()), ("b", pa.string())])
    t = pa.table({
        "c": pa.array(
            [
                None,                       # null list
                [],                         # empty list
                [None],                     # null element
                [{"a": None, "b": None}],   # null members
                [{"a": 1, "b": "x"}, None],
            ] * 40,
            pa.list_(elem),
        ),
    })
    p = str(tmp_path / "v2n.parquet")
    pq.write_table(t, p, data_page_version="2.0", use_dictionary=False,
                   compression="snappy")
    _assert_matches_pyarrow(p, backend)
    with FileReader(p, backend=backend) as r:
        rows = [x["c"] for x in r.iter_rows()]
    assert rows[:5] == t.column("c").to_pylist()[:5]

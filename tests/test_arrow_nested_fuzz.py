"""Differential fuzz of the nested-to-Arrow builder: RANDOM schema shapes.

The targeted suite (test_arrow_nested.py) pins named shapes; this one
generates arbitrary nestings — structs in lists in maps in structs, to
depth 4, with independent null probabilities at every level — writes them
with pyarrow under randomized row-group sizes and encodings, and requires
to_arrow to equal pyarrow.parquet.read_table on every column of every
seed. The Dremel level math has exactly the kind of corners (placeholder
dropping, slot alignment, validity thresholds) that only random shapes
find.
"""

import datetime as dt

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader

N_SEEDS = 20
N_ROWS = 300

_LEAVES = [
    pa.int64(),
    pa.int32(),
    pa.float64(),
    pa.string(),
    pa.bool_(),
    pa.date32(),
    pa.timestamp("us"),
]


def _rand_type(rng, depth):
    if depth >= 4 or rng.random() < 0.45:
        return _LEAVES[int(rng.integers(0, len(_LEAVES)))]
    k = rng.random()
    if k < 0.4:
        return pa.list_(_rand_type(rng, depth + 1))
    if k < 0.75:
        n = int(rng.integers(1, 4))
        return pa.struct(
            [(f"f{j}", _rand_type(rng, depth + 1)) for j in range(n)]
        )
    return pa.map_(pa.string(), _rand_type(rng, depth + 1))


def _rand_value(rng, typ, depth=0):
    if rng.random() < (0.15 if depth else 0.1):
        return None
    if pa.types.is_list(typ):
        return [
            _rand_value(rng, typ.value_type, depth + 1)
            for _ in range(int(rng.integers(0, 4)))
        ]
    if pa.types.is_struct(typ):
        return {
            f.name: _rand_value(rng, f.type, depth + 1) for f in typ
        }
    if pa.types.is_map(typ):
        return [
            (f"k{j}", _rand_value(rng, typ.item_type, depth + 1))
            for j in range(int(rng.integers(0, 3)))
        ]
    if typ == pa.int64():
        return int(rng.integers(-(2**62), 2**62))
    if typ == pa.int32():
        return int(rng.integers(-(2**31), 2**31))
    if typ == pa.float64():
        return float(rng.standard_normal())
    if typ == pa.string():
        return f"s{int(rng.integers(0, 40))}" * int(rng.integers(0, 3))
    if typ == pa.bool_():
        return bool(rng.random() < 0.5)
    if typ == pa.date32():
        return dt.date(1970, 1, 1) + dt.timedelta(int(rng.integers(-10000, 10000)))
    if typ == pa.timestamp("us"):
        return dt.datetime(2000, 1, 1) + dt.timedelta(
            seconds=int(rng.integers(0, 10**9))
        )
    raise AssertionError(typ)


@pytest.mark.parametrize("backend", ["host", "tpu_roundtrip"])
@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_nested_shapes_match_pyarrow(tmp_path, seed, backend):
    rng = np.random.default_rng(5_000_000 + seed)
    n_cols = int(rng.integers(1, 4))
    cols = {}
    for ci in range(n_cols):
        typ = _rand_type(rng, 0)
        vals = [_rand_value(rng, typ) for _ in range(N_ROWS)]
        cols[f"c{ci}"] = pa.array(vals, typ)
    t = pa.table(cols)
    p = str(tmp_path / f"fz{seed}.parquet")
    pq.write_table(
        t,
        p,
        row_group_size=int(rng.choice([64, 128, N_ROWS])),
        compression=str(rng.choice(["snappy", "zstd", "none"])),
        use_dictionary=bool(rng.random() < 0.5),
        data_page_version=str(rng.choice(["1.0", "2.0"])),
    )
    want = pq.read_table(p)
    with FileReader(p, backend=backend) as r:
        out = r.to_arrow()
    for name in want.column_names:
        got = out.column(name).to_pylist()
        exp = want.column(name).to_pylist()
        assert got == exp, (seed, name, t.schema.field(name).type)
    # row lane agrees too (three-way: pyarrow / columnar / rows)
    with FileReader(p, backend=backend) as r:
        rows = list(r.iter_rows())
    exp_rows = want.to_pylist()
    assert len(rows) == len(exp_rows)
    for i, (g, w) in enumerate(zip(rows, exp_rows)):
        for name in want.column_names:
            typ = want.schema.field(name).type
            gn = _norm_by_type(g[name], typ)
            wn = _norm_by_type(w[name], typ)
            assert gn == wn, (seed, i, name, g[name], w[name])


def _norm_by_type(v, typ):
    """Type-DRIVEN normalization: maps compare as dicts (pyarrow's
    to_pylist yields pair lists, our rows yield dicts — an empty map is
    ambiguous without the type), lists recurse by value type."""
    if v is None:
        return None
    if pa.types.is_map(typ):
        pairs = v.items() if isinstance(v, dict) else v
        return {k: _norm_by_type(x, typ.item_type) for k, x in pairs}
    if pa.types.is_list(typ) or pa.types.is_large_list(typ):
        return [_norm_by_type(x, typ.value_type) for x in v]
    if pa.types.is_struct(typ):
        return {f.name: _norm_by_type(v.get(f.name), f.type) for f in typ}
    return v


# -- write-side mirror: random nesting through OUR shredder -------------------

from parquet_tpu import FileWriter  # noqa: E402
from parquet_tpu.schema.builder import (  # noqa: E402
    Type,
    group,
    list_of,
    map_of,
    message,
    optional,
    required,
    string,
)


def _rand_field(rng, name, depth):
    """(Column, generator) for one random field of our builder schema."""
    rep_opt = bool(rng.random() < 0.6)
    wrap = optional if rep_opt else required
    null_p = 0.2 if rep_opt else 0.0

    def nullable(gen):
        return lambda r: None if r.random() < null_p else gen(r)

    if depth >= 3 or rng.random() < 0.45:
        k = rng.random()
        if k < 0.4:
            return wrap(name, Type.INT64), nullable(
                lambda r: int(r.integers(-(2**62), 2**62))
            )
        if k < 0.7:
            return wrap(name, string()), nullable(
                lambda r: f"v{int(r.integers(0, 30))}"
            )
        return wrap(name, Type.DOUBLE), nullable(lambda r: float(r.standard_normal()))
    k = rng.random()
    if k < 0.35:
        elem, egen = _rand_field(rng, "element", depth + 1)
        col = list_of(name, elem, required_list=not rep_opt)
        return col, nullable(
            lambda r: [egen(r) for _ in range(int(r.integers(0, 4)))]
        )
    if k < 0.65:
        subs = [_rand_field(rng, f"g{j}", depth + 1) for j in range(int(rng.integers(1, 4)))]
        col = group(name, *[c for c, _ in subs])
        if not rep_opt:
            col.element.repetition_type = 0  # REQUIRED group
        gens = [(c.element.name, g) for c, g in subs]
        return col, nullable(lambda r: {n: g(r) for n, g in gens})
    vcol, vgen = _rand_field(rng, "value", depth + 1)
    col = map_of(name, required("key", string()), vcol, required_map=not rep_opt)
    return col, nullable(
        lambda r: {f"k{j}": vgen(r) for j in range(int(r.integers(0, 3)))}
    )


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_nested_write_read_by_pyarrow(tmp_path, seed):
    """OUR writer's shredder over random nesting: pyarrow (cross-impl) and
    our own reader must both reproduce the rows."""
    rng = np.random.default_rng(7_000_000 + seed)
    fields = []
    gens = []
    for ci in range(int(rng.integers(1, 4))):
        col, gen = _rand_field(rng, f"c{ci}", 0)
        fields.append(col)
        gens.append((f"c{ci}", gen))
    schema = message(*fields)
    rows = [{n: g(rng) for n, g in gens} for _ in range(200)]
    p = str(tmp_path / f"w{seed}.parquet")
    with FileWriter(
        p, schema,
        codec=str(rng.choice(["snappy", "zstd", "uncompressed"])),
        data_page_version=int(rng.choice([1, 2])),
        enable_dictionary=bool(rng.random() < 0.5),
    ) as w:
        w.write_rows(rows)
    # cross-implementation read
    pa_rows = pq.read_table(p).to_pylist()
    assert len(pa_rows) == len(rows)
    want_t = pq.read_table(p)
    for i, (w_row, exp) in enumerate(zip(pa_rows, rows)):
        for name, _ in gens:
            typ = want_t.schema.field(name).type
            assert _norm_by_type(w_row[name], typ) == _norm_by_type(exp[name], typ), (
                seed, i, name
            )
    # our own reader agrees
    with FileReader(p) as r:
        ours = list(r.iter_rows())
    for i, (o, exp) in enumerate(zip(ours, rows)):
        for name, _ in gens:
            typ = want_t.schema.field(name).type
            assert _norm_by_type(o[name], typ) == _norm_by_type(exp[name], typ), (
                seed, i, name
            )
    # and the columnar lane
    with FileReader(p) as r:
        tbl = r.to_arrow()
    for name, _ in gens:
        assert tbl.column(name).to_pylist() == want_t.column(name).to_pylist(), (
            seed, name
        )

"""LZ4 codec coverage: raw block format (LZ4_RAW, codec 7) and the legacy
Hadoop-framed LZ4 (codec 5), native C implementation cross-validated against
pyarrow's bundled lz4 in both directions, plus decoder fuzzing.

The reference treats LZ4 as a user-registered plugin (reference:
compress.go:131-136, README.md:101-111); here both wire forms are built in,
and the native whole-chunk prepare walk handles them so LZ4 files keep the
device decode path.
"""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema
from parquet_tpu.core.compress import (
    CompressionError,
    compress_block,
    decompress_block,
)
from parquet_tpu.meta.parquet_types import CompressionCodec
from parquet_tpu.utils.native import get_native

lib = get_native()
needs_native = pytest.mark.skipif(
    lib is None or not lib.has_lz4, reason="native lz4 not built"
)


def _payloads():
    rng = np.random.default_rng(7)
    return [
        b"",
        b"x",
        b"hello world " * 400,  # match-heavy
        rng.integers(0, 256, 4096, dtype=np.uint8).tobytes(),  # incompressible
        rng.integers(0, 9, 100_000, dtype=np.int64).tobytes(),  # mixed
        b"\x00" * 70_000,  # long RLE overlap matches + length extensions
    ]


class TestLz4Block:
    @needs_native
    def test_roundtrip_and_cross_validation(self):
        pa_raw = pa.Codec("lz4_raw")
        for data in _payloads():
            c = lib.lz4_compress(data)
            assert bytes(lib.lz4_decompress(c, len(data))) == data
            # canonical decoder accepts our blocks (end-of-block rules upheld)
            assert bytes(pa_raw.decompress(c, decompressed_size=len(data))) == data
            # we accept canonical blocks
            pc = bytes(pa_raw.compress(data))
            assert bytes(lib.lz4_decompress(pc, len(data))) == data

    @needs_native
    def test_decoder_rejects_corrupt(self):
        data = b"some reasonably long payload " * 50
        c = bytearray(lib.lz4_compress(data))
        with pytest.raises(ValueError):
            lib.lz4_decompress(bytes(c), len(data) + 1)  # wrong size
        with pytest.raises(ValueError):
            lib.lz4_decompress(bytes(c[: len(c) // 2]), len(data))  # truncated
        # offset-before-start: token with match, offset 0
        with pytest.raises(ValueError):
            lib.lz4_decompress(b"\x14AAAA\x00\x00", 64)

    @needs_native
    def test_decoder_fuzz_no_crash(self):
        rng = np.random.default_rng(1234)
        data = b"fuzz seed payload " * 64
        base = lib.lz4_compress(data)
        for _ in range(600):
            buf = bytearray(base)
            for _ in range(rng.integers(1, 8)):
                buf[rng.integers(0, len(buf))] = rng.integers(0, 256)
            try:
                out = lib.lz4_decompress(bytes(buf), len(data))
                assert len(out) == len(data)  # either clean error or full size
            except ValueError:
                pass
        for _ in range(300):
            junk = rng.integers(0, 256, rng.integers(0, 200), dtype=np.uint8)
            try:
                lib.lz4_decompress(junk.tobytes(), 512)
            except ValueError:
                pass

    def test_block_api_lz4_raw(self):
        data = b"registry-level block roundtrip " * 100
        c = compress_block(data, CompressionCodec.LZ4_RAW)
        assert bytes(decompress_block(c, CompressionCodec.LZ4_RAW, len(data))) == data
        with pytest.raises(CompressionError):
            decompress_block(c[:5], CompressionCodec.LZ4_RAW, len(data))

    def test_block_api_lz4_hadoop_framed_and_bare(self):
        data = b"hadoop framing " * 300
        framed = compress_block(data, CompressionCodec.LZ4)
        # framed form: 8-byte BE header precedes the block
        import struct

        usz, csz = struct.unpack(">II", bytes(framed[:8]))
        assert usz == len(data) and csz == len(framed) - 8
        assert bytes(decompress_block(framed, CompressionCodec.LZ4, len(data))) == data
        # bare raw block also accepted on read (parquet-cpp contract)
        bare = compress_block(data, CompressionCodec.LZ4_RAW)
        assert bytes(decompress_block(bare, CompressionCodec.LZ4, len(data))) == data


class TestLz4Files:
    def _table(self, n=20_000):
        return pa.table(
            {
                "a": pa.array(range(n), pa.int64()),
                "s": pa.array([f"val{i % 97}" for i in range(n)]),
            }
        )

    def test_pyarrow_lz4_file_both_backends(self, tmp_path):
        t = self._table()
        path = str(tmp_path / "pa_lz4.parquet")
        pq.write_table(t, path, compression="lz4", use_dictionary=False)
        expect = t.to_pylist()
        for backend in ("host", "tpu_roundtrip"):
            with FileReader(path, backend=backend) as r:
                assert list(r.iter_rows()) == expect, backend

    @pytest.mark.parametrize("codec", ["lz4", "lz4_raw"])
    def test_our_lz4_file_read_by_pyarrow(self, tmp_path, codec):
        t = self._table(5_000)
        out = io.BytesIO()
        schema = parse_schema(
            "message m { required int64 a; required binary s (STRING); }"
        )
        with FileWriter(out, schema, codec=codec) as w:
            w.write_rows(t.to_pylist())
        out.seek(0)
        assert pq.read_table(out).to_pylist() == t.to_pylist()

    def test_hadoop_multiblock_write_framing(self, tmp_path):
        """Pages past Hadoop's 256KB codec buffer must emit MULTIPLE
        [usz][csz][block] frames, as parquet-mr's BlockCompressorStream
        does — pinned by parsing the raw chunk bytes — and still read back
        identically via pyarrow, our host walk, and the native chunk walk."""
        import struct

        from parquet_tpu.core.compress import _Lz4Hadoop
        from parquet_tpu.meta.parquet_types import CompressionCodec

        n = 120_000  # ~960KB of int64 -> 4 frames at 256KB
        vals = np.arange(n, dtype=np.int64) * 3
        schema = parse_schema("message m { required int64 a; }")
        path = str(tmp_path / "mb_lz4.parquet")
        with FileWriter(
            path, schema, codec="lz4", max_page_size=1 << 21,
            enable_dictionary=False,
        ) as w:
            w.write_column("a", vals)
        # pyarrow (parquet-cpp) reads our multi-block framing
        assert pq.read_table(path).column("a").to_pylist() == vals.tolist()
        for backend in ("host", "tpu_roundtrip"):
            with FileReader(path, backend=backend) as r:
                got = np.asarray(r.read_row_group(0)[("a",)].values)
            np.testing.assert_array_equal(got, vals)
        # the chunk's compressed bytes really hold >1 Hadoop frame
        with FileReader(path) as r:
            cc = r.metadata.row_groups[0].columns[0]
            md = cc.meta_data
            with open(path, "rb") as f:
                f.seek(md.data_page_offset)
                raw = f.read(md.total_compressed_size)
        # skip the page header: find the first frame by scanning for a
        # plausible [usz][csz] pair summing over the remaining bytes
        blk = _Lz4Hadoop._BLOCK
        frames = 0
        for start in range(len(raw) - 8):
            pos, total_u = start, 0
            k = 0
            while pos + 8 <= len(raw):
                usz, csz = struct.unpack_from(">II", raw, pos)
                if usz == 0 or usz > blk or pos + 8 + csz > len(raw):
                    break
                total_u += usz
                pos += 8 + csz
                k += 1
            if total_u == n * 8 and pos == len(raw):
                frames = k
                break
        assert frames >= 4, frames

    def test_lz4_device_batches(self, tmp_path):
        t = self._table()
        path = str(tmp_path / "batch_lz4.parquet")
        pq.write_table(t, path, compression="lz4", use_dictionary=False)
        with FileReader(path) as r:
            b = next(r.iter_device_batches(8_192, columns=[("a",)]))
            np.testing.assert_array_equal(
                np.asarray(b[("a",)]), np.arange(8_192, dtype=np.int64)
            )

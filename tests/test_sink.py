"""parquet_tpu.sink tests: the ByteSink contract, the atomic-commit /
abort-on-error guarantees, and the parallel encode pipeline's one hard
promise — output bytes IDENTICAL to the serial writer, or a typed
WriterError and an uncommitted destination, never a torn file.
"""

import io
import os
import tempfile

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter, WriterError
from parquet_tpu.schema.dsl import parse_schema
from parquet_tpu.sink import (
    BufferedSink,
    ByteSink,
    FileObjectSink,
    LocalFileSink,
    MemorySink,
    SinkError,
    open_sink,
)
from parquet_tpu.testing.flaky import FlakySink
from parquet_tpu.utils import metrics

SCHEMA = parse_schema(
    "message m { required int64 id; required binary name (UTF8); "
    "optional double x; }"
)


def _tmp_leftovers(d):
    return [f for f in os.listdir(d) if f.endswith(".tmp")]


def _write_groups(sink, n_groups=3, rows=500, **kw):
    w = FileWriter(sink, SCHEMA, **kw)
    for g in range(n_groups):
        w.write_column("id", np.arange(g * rows, (g + 1) * rows, dtype=np.int64))
        w.write_column("name", [f"n{i % 37}" for i in range(rows)])
        w.write_column(
            "x", np.arange(rows) * 0.5, def_levels=np.ones(rows, dtype=np.uint16)
        )
        w.flush_row_group()
    return w


class TestLocalFileSink:
    def test_atomic_commit(self, tmp_path):
        path = tmp_path / "out.bin"
        s = LocalFileSink(path)
        s.write(b"hello ")
        s.write(b"world")
        assert s.tell() == 11
        # nothing visible at the destination until commit
        assert not path.exists()
        assert _tmp_leftovers(tmp_path)
        s.close()
        assert path.read_bytes() == b"hello world"
        assert _tmp_leftovers(tmp_path) == []
        s.close()  # idempotent

    def test_abort_leaves_nothing(self, tmp_path):
        path = tmp_path / "out.bin"
        s = LocalFileSink(path)
        s.write(b"partial")
        s.abort()
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []
        s.abort()  # idempotent
        with pytest.raises(SinkError):
            s.write(b"more")

    def test_abort_after_commit_is_noop(self, tmp_path):
        path = tmp_path / "out.bin"
        s = LocalFileSink(path)
        s.write(b"data")
        s.close()
        s.abort()  # must NOT unlink the committed file
        assert path.read_bytes() == b"data"

    def test_commit_replaces_existing(self, tmp_path):
        path = tmp_path / "out.bin"
        path.write_bytes(b"old contents")
        s = LocalFileSink(path)
        s.write(b"new")
        # the old file is intact while the new one is being written
        assert path.read_bytes() == b"old contents"
        s.close()
        assert path.read_bytes() == b"new"

    def test_context_manager_exception_aborts(self, tmp_path):
        path = tmp_path / "out.bin"
        with pytest.raises(RuntimeError):
            with LocalFileSink(path) as s:
                s.write(b"doomed")
                raise RuntimeError("boom")
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []


class TestOtherSinks:
    def test_memory_sink(self):
        s = MemorySink()
        s.write(b"ab")
        s.write(b"cd")
        assert s.tell() == 4
        assert s.getvalue() == b"abcd"
        s.close()
        with pytest.raises(SinkError):
            s.write(b"e")
        assert s.getvalue() == b"abcd"  # readable after close

    def test_file_object_sink_never_closes_caller_object(self):
        buf = io.BytesIO()
        s = FileObjectSink(buf)
        s.write(b"xyz")
        assert s.tell() == 3
        s.close()
        assert not buf.closed  # caller owns the lifetime
        assert buf.getvalue() == b"xyz"

    def test_buffered_sink_spills_at_threshold(self):
        inner = MemorySink()
        s = BufferedSink(inner, spill_bytes=10)
        s.write(b"abc")
        assert inner.tell() == 0 and s.buffered() == 3  # held
        s.write(b"defghijkl")  # 12 total >= 10: spills
        assert inner.tell() == 12 and s.buffered() == 0
        s.write(b"mn")
        assert s.tell() == 14  # position counts buffered bytes
        s.flush()
        assert inner.getvalue() == b"abcdefghijklmn"
        # write-combining is visible in the metrics: 14 bytes, 2 inner calls
        s.close()

    def test_buffered_sink_abort_drops_buffer(self, tmp_path):
        path = tmp_path / "o.bin"
        s = BufferedSink(LocalFileSink(path), spill_bytes=1 << 20)
        s.write(b"buffered only")
        s.abort()
        assert not path.exists()
        with pytest.raises(SinkError):  # not a silent buffered no-op
            s.write(b"more")

    def test_base_abort_never_commits(self):
        # a minimal subclass whose close() IS its commit: the inherited
        # abort() must not publish (the default is discard, not close)
        class CommitOnClose(ByteSink):
            committed = False

            def write(self, data):
                return len(data)

            def tell(self):
                return 0

            def close(self):
                self.committed = True

        s = CommitOnClose()
        s.abort()
        assert not s.committed

    def test_short_writing_file_object_rejected(self):
        class ShortWriter:
            def write(self, b):
                return max(len(b) - 1, 0)

        s = FileObjectSink(ShortWriter())
        with pytest.raises(SinkError):
            s.write(b"abcd")

    def test_non_oserror_sink_fault_poisons_writer(self, tmp_path):
        # duck-typed custom sinks may raise transport exceptions that are
        # not OSErrors; the writer must still poison + abort, not let a
        # later close() commit with _pos desynced from the sink
        class WeirdFault(MemorySink):
            def write(self, data):
                if self.tell() > 100:
                    raise RuntimeError("transport hiccup")
                return super().write(data)

        w = FileWriter(WeirdFault(), SCHEMA)
        with pytest.raises(WriterError):
            w.write_column("id", np.arange(100, dtype=np.int64))
            w.write_column("name", ["z"] * 100)
            w.write_column("x", np.zeros(100))
            w.flush_row_group()
        assert w.close() is None  # poisoned: no footer commit

    def test_open_sink_coercions(self, tmp_path):
        s, owns = open_sink(str(tmp_path / "a.bin"))
        assert isinstance(s, LocalFileSink) and owns
        s.abort()
        mem = MemorySink()
        s, owns = open_sink(mem)
        assert s is mem and not owns
        buf = io.BytesIO()
        s, owns = open_sink(buf)
        assert isinstance(s, FileObjectSink) and not owns
        with pytest.raises(TypeError):
            open_sink(12345)


class TestWriterThroughSinks:
    def test_path_write_is_atomic(self, tmp_path):
        path = tmp_path / "f.parquet"
        w = _write_groups(str(path))
        # pre-close: the destination does not exist yet (no torn reads for
        # glob-driven datasets picking up half-written shards)
        assert not path.exists()
        w.close()
        assert pq.read_table(str(path)).num_rows == 1500
        assert _tmp_leftovers(tmp_path) == []

    def test_memory_sink_writer(self):
        sink = MemorySink()
        _write_groups(sink).close()
        got = pq.read_table(io.BytesIO(sink.getvalue()))
        assert got.num_rows == 1500

    def test_buffered_sink_same_bytes(self, tmp_path):
        plain = MemorySink()
        _write_groups(plain).close()
        inner = MemorySink()
        buffered = BufferedSink(inner, spill_bytes=64 << 10)
        _write_groups(buffered).close()
        assert inner.getvalue() == plain.getvalue()

    def test_exception_in_with_block_aborts(self, tmp_path):
        path = tmp_path / "f.parquet"
        with pytest.raises(RuntimeError):
            with FileWriter(str(path), SCHEMA) as w:
                w.write_column("id", np.arange(10, dtype=np.int64))
                w.write_column("name", ["a"] * 10)
                w.write_column("x", np.zeros(10))
                w.flush_row_group()
                raise RuntimeError("user code blew up")
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []

    def test_close_idempotent_and_abort_after_close_noop(self, tmp_path):
        path = tmp_path / "f.parquet"
        w = _write_groups(str(path), n_groups=1)
        meta = w.close()
        assert meta is not None and w.close() is meta  # idempotent
        w.abort()  # after commit: must not destroy the file
        assert path.exists()
        with pytest.raises(WriterError):
            w.write_row({"id": 1, "name": "x"})


CODECS = ["uncompressed", "snappy", "gzip"]


class TestParallelSerialDifferential:
    """The pipeline's hard promise: parallel output is BYTE-identical to
    serial, across encodings x codecs x row-group counts."""

    def _payload(self, schema_text, cols, n_groups, rows, **kw):
        schema = parse_schema(schema_text)

        def write(parallel):
            sink = MemorySink()
            w = FileWriter(sink, schema, **kw, parallel=parallel)
            for g in range(n_groups):
                for name, make in cols.items():
                    w.write_column(name, make(g, rows))
                w.flush_row_group()
            w.close()
            return sink.getvalue()

        serial = write(False)
        for pool in (2, 4):
            assert write(pool) == serial, f"pool={pool} diverged"
        return serial

    @pytest.mark.parametrize("codec", CODECS)
    @pytest.mark.parametrize("dpv", [1, 2])
    def test_flat_matrix(self, codec, dpv):
        # per-group data must be a pure function of g (both writers see
        # identical input); a shared rng stream would differ per call
        data = self._payload(
            "message m { required int64 a; required binary s (UTF8); "
            "required double d; required boolean b; }",
            {
                "a": lambda g, n: np.arange(g * n, (g + 1) * n, dtype=np.int64),
                "s": lambda g, n: [f"k{(g * 31 + i) % 59}" for i in range(n)],
                "d": lambda g, n: np.random.default_rng(g).random(n),
                "b": lambda g, n: (np.arange(n) % 3 == 0),
            },
            n_groups=4,
            rows=700,
            codec=codec,
            data_page_version=dpv,
            column_encodings={"a": "DELTA_BINARY_PACKED"},
        )
        got = pq.read_table(io.BytesIO(data))
        assert got.num_rows == 2800

    def test_row_group_counts(self):
        for n_groups in (1, 3, 8):
            self._payload(
                "message m { required int64 a; }",
                {"a": lambda g, n: np.arange(g * n, (g + 1) * n, dtype=np.int64)},
                n_groups=n_groups,
                rows=200,
                codec="snappy",
            )

    def test_encodings_and_features(self):
        # delta byte array + page index + blooms + crc through the pipeline
        self._payload(
            "message m { required binary s (UTF8); required int32 v; }",
            {
                "s": lambda g, n: [f"prefix_{g}_{i:06d}" for i in range(n)],
                "v": lambda g, n: np.arange(n, dtype=np.int32) % 50,
            },
            n_groups=4,
            rows=400,
            codec="gzip",
            column_encodings={"s": "DELTA_BYTE_ARRAY"},
            use_dictionary=["v"],
            write_page_index=True,
            bloom_filters=["v"],
            with_crc=True,
        )

    def test_row_path_and_metadata_kv(self):
        def write(parallel):
            sink = MemorySink()
            w = FileWriter(sink, SCHEMA, codec="snappy", parallel=parallel)
            for g in range(3):
                for i in range(300):
                    w.write_row(
                        {"id": g * 300 + i, "name": f"r{i % 11}", "x": i / 7}
                    )
                w.flush_row_group(metadata={"group": str(g)})
            w.close()
            return sink.getvalue()

        assert write(False) == write(3)

    @pytest.mark.slow
    def test_full_matrix_slow(self):
        """Extended sweep: every fallback encoding x codec x dpv."""
        for codec in CODECS:
            for dpv in (1, 2):
                for enc, schema_text, make in [
                    (
                        {"a": "DELTA_BINARY_PACKED"},
                        "message m { required int32 a; }",
                        {"a": lambda g, n: np.random.default_rng(g).integers(-(1 << 20), 1 << 20, n).astype(np.int32)},
                    ),
                    (
                        {"s": "DELTA_LENGTH_BYTE_ARRAY"},
                        "message m { required binary s; }",
                        {"s": lambda g, n: [b"v%d" % (i * 3) for i in range(n)]},
                    ),
                    (
                        {"f": "BYTE_STREAM_SPLIT"},
                        "message m { required float f; }",
                        {"f": lambda g, n: np.random.default_rng(g).random(n).astype(np.float32)},
                    ),
                    (
                        {"b": "RLE"},
                        "message m { required boolean b; }",
                        {"b": lambda g, n: (np.random.default_rng(g).random(n) < 0.3)},
                    ),
                ]:
                    self._payload(
                        schema_text, make, n_groups=5, rows=333,
                        codec=codec, data_page_version=dpv,
                        column_encodings=enc, use_dictionary=False,
                    )


def _fused_available() -> bool:
    from parquet_tpu.utils.native import get_native

    lib = get_native()
    return lib is not None and getattr(lib, "has_chunk_encode", False)


def _write_cols(schema_text, cols, n_groups=3, rows=700, **kw) -> bytes:
    schema = parse_schema(schema_text)
    sink = MemorySink()
    w = FileWriter(sink, schema, **kw)
    for g in range(n_groups):
        for name, make in cols.items():
            w.write_column(name, make(g, rows))
        w.flush_row_group()
    w.close()
    return sink.getvalue()


@pytest.mark.skipif(not _fused_available(), reason="native chunk_encode not built")
class TestFusedEncodeLadder:
    """The fused native encode walk's hard promise: bytes IDENTICAL to the
    staged Python encoder (PQT_FUSED_ENCODE=0) for every shape it accepts,
    a counted decline for shapes it doesn't, and a counted staged recovery
    for faults — never divergent output, never a torn sink."""

    MATRIX_COLS = {
        "a": lambda g, n: np.arange(g * n, (g + 1) * n, dtype=np.int64),
        "s": lambda g, n: [f"k{(g * 31 + i) % 59}" for i in range(n)],
        "hi": lambda g, n: [f"u{g}_{i}" for i in range(n)],  # all-unique strings
        "d": lambda g, n: np.random.default_rng(g).random(n),
        "ts": lambda g, n: np.arange(n, dtype=np.int64) * 3 + g,
    }
    MATRIX_SCHEMA = (
        "message m { required int64 a; required binary s (UTF8); "
        "required binary hi (UTF8); required double d; required int64 ts; }"
    )

    def _differential(self, schema_text, cols, **kw):
        fused = _write_cols(schema_text, cols, **kw)
        os.environ["PQT_FUSED_ENCODE"] = "0"
        try:
            staged = _write_cols(schema_text, cols, **kw)
        finally:
            del os.environ["PQT_FUSED_ENCODE"]
        assert fused == staged
        return fused

    @pytest.mark.parametrize("codec", CODECS)
    @pytest.mark.parametrize("dpv", [1, 2])
    def test_flat_matrix_byte_identical(self, codec, dpv):
        s0 = metrics.snapshot()
        data = self._differential(
            self.MATRIX_SCHEMA,
            self.MATRIX_COLS,
            codec=codec,
            data_page_version=dpv,
            column_encodings={"ts": "DELTA_BINARY_PACKED"},
        )
        d = metrics.delta(s0)
        assert d.get('events_total{event="encode_fused_engaged"}', 0) > 0
        got = pq.read_table(io.BytesIO(data))
        assert got.num_rows == 2100

    RLE_BOOL_SCHEMA = (
        "message m { required boolean flag; required boolean runs; "
        "required int64 a; }"
    )
    RLE_BOOL_COLS = {
        # alternating short runs and literal-dense stretches exercise both
        # arms of the width-1 hybrid stream
        "flag": lambda g, n: np.random.default_rng(g).random(n) < 0.5,
        "runs": lambda g, n: (np.arange(n) // (37 + g)) % 2 == 0,
        "a": lambda g, n: np.arange(g * n, (g + 1) * n, dtype=np.int64),
    }

    @pytest.mark.parametrize("codec", CODECS)
    @pytest.mark.parametrize("dpv", [1, 2])
    def test_rle_boolean_byte_identical(self, codec, dpv):
        """RLE-boolean value route: the 4-byte-prefixed width-1 hybrid
        stream (present in BOTH page versions — the prefix belongs to the
        VALUE encoding, unlike dpv2 def levels) must leave the fused walk
        byte-identical to the staged encoder instead of declining the
        whole chunk."""
        s0 = metrics.snapshot()
        data = self._differential(
            self.RLE_BOOL_SCHEMA,
            self.RLE_BOOL_COLS,
            codec=codec,
            data_page_version=dpv,
            column_encodings={"flag": "RLE", "runs": "RLE"},
        )
        d = metrics.delta(s0)
        assert d.get('events_total{event="encode_fused_engaged"}', 0) > 0
        assert not d.get('events_total{event="encode_fused_declined"}', 0)
        # readback through our own reader (pyarrow's RLE-bool support is
        # not the contract here)
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "rle.parquet")
            with open(p, "wb") as f:
                f.write(data)
            with FileReader(p) as r:
                rows = list(r.iter_rows())
        assert len(rows) == 2100
        for g in range(3):
            want = self.RLE_BOOL_COLS["flag"](g, 700)
            got = np.array([x["flag"] for x in rows[g * 700 : (g + 1) * 700]])
            np.testing.assert_array_equal(got, want)

    def test_rle_boolean_multi_page(self):
        """Tiny max_page_size: every page re-emits its own length prefix
        and the staged/fused page boundaries must land identically."""
        for dpv in (1, 2):
            self._differential(
                "message m { required boolean flag; }",
                {"flag": self.RLE_BOOL_COLS["flag"]},
                rows=5000,
                codec="uncompressed",
                data_page_version=dpv,
                max_page_size=512,
                column_encodings={"flag": "RLE"},
            )

    @pytest.mark.parametrize("codec", CODECS)
    @pytest.mark.parametrize("dpv", [1, 2])
    def test_crc_and_optional_levels(self, dpv, codec):
        schema = parse_schema(
            "message m { required int64 a; optional binary s (UTF8); }"
        )

        def write():
            sink = MemorySink()
            w = FileWriter(
                sink, schema, codec=codec, with_crc=True,
                data_page_version=dpv,
            )
            rng = np.random.default_rng(5)
            for g in range(3):
                n = 900
                dl = (rng.random(n) < 0.8).astype(np.uint16)
                vals = [f"v{i % 17}" for i in range(int(dl.sum()))]
                w.write_column("a", np.arange(n, dtype=np.int64) * 7)
                w.write_column("s", vals, def_levels=dl)
                w.flush_row_group()
            w.close()
            return sink.getvalue()

        fused = write()
        os.environ["PQT_FUSED_ENCODE"] = "0"
        try:
            staged = write()
        finally:
            del os.environ["PQT_FUSED_ENCODE"]
        assert fused == staged
        got = pq.read_table(io.BytesIO(fused))
        assert got.num_rows == 2700

    def test_multi_page_and_tiny_page_split(self):
        # tiny max_page_size forces many pages through the fused splitter
        self._differential(
            "message m { required int64 a; required binary s (UTF8); }",
            {
                "a": lambda g, n: np.arange(n, dtype=np.int64),
                "s": lambda g, n: [f"s{i % 13}" for i in range(n)],
            },
            n_groups=2,
            rows=2000,
            codec="snappy",
            max_page_size=512,
        )

    def test_fixed_len_and_float32(self):
        self._differential(
            "message m { required fixed_len_byte_array(6) f; "
            "required float r; }",
            {
                "f": lambda g, n: [bytes([g, i % 251, 3, 4, 5, 6]) for i in range(n)],
                "r": lambda g, n: np.random.default_rng(g).random(n).astype(
                    np.float32
                ),
            },
            n_groups=2,
            rows=500,
            use_dictionary=False,
        )

    def test_empty_and_single_row_groups(self):
        schema = parse_schema("message m { required int64 a; }")

        def write():
            sink = MemorySink()
            w = FileWriter(sink, schema, codec="gzip")
            w.write_column("a", np.array([7], dtype=np.int64))
            w.flush_row_group()
            w.close()
            return sink.getvalue()

        fused = write()
        os.environ["PQT_FUSED_ENCODE"] = "0"
        try:
            staged = write()
        finally:
            del os.environ["PQT_FUSED_ENCODE"]
        assert fused == staged

    def test_ineligible_shapes_decline_to_staged(self):
        # nested column (max_rep > 0), BSS encoding, page index: all must
        # DECLINE (counter) and still produce correct files
        s0 = metrics.snapshot()
        data = _write_cols(
            "message m { required float f; }",
            {"f": lambda g, n: np.random.default_rng(g).random(n).astype(np.float32)},
            n_groups=1,
            rows=300,
            column_encodings={"f": "BYTE_STREAM_SPLIT"},
            use_dictionary=False,
        )
        d = metrics.delta(s0)
        assert d.get('events_total{event="encode_fused_declined"}', 0) > 0
        assert d.get('events_total{event="encode_fused_engaged"}', 0) == 0
        pq.read_table(io.BytesIO(data))
        # page index keeps the staged rung (per-page stats live there)
        s0 = metrics.snapshot()
        _write_cols(
            "message m { required int64 a; }",
            {"a": lambda g, n: np.arange(n, dtype=np.int64)},
            n_groups=1,
            rows=300,
            write_page_index=True,
        )
        d = metrics.delta(s0)
        assert d.get('events_total{event="encode_fused_engaged"}', 0) == 0

    def test_native_fault_recovers_on_staged_rung(self, monkeypatch):
        """A native-walk abort mid-ladder must fall back to the staged rung
        byte-identically and count the recovery."""
        from parquet_tpu.utils import native as native_mod
        from parquet_tpu.utils.native import EncodeFault

        lib = native_mod.get_native()
        real = lib.chunk_encode

        def faulty(*a, **kw):
            return EncodeFault(code=-1, stage="values", page=0)

        staged_oracle = _write_cols(
            "message m { required int64 a; }",
            {"a": lambda g, n: np.arange(n, dtype=np.int64) % 9},
            n_groups=2,
            rows=400,
            codec="snappy",
        )
        monkeypatch.setattr(lib, "chunk_encode", faulty)
        s0 = metrics.snapshot()
        recovered = _write_cols(
            "message m { required int64 a; }",
            {"a": lambda g, n: np.arange(n, dtype=np.int64) % 9},
            n_groups=2,
            rows=400,
            codec="snappy",
        )
        monkeypatch.setattr(lib, "chunk_encode", real)
        d = metrics.delta(s0)
        assert recovered == staged_oracle
        assert d.get('events_total{event="encode_fallback_recovered"}', 0) > 0
        assert d.get('events_total{event="encode_fused_fault_values"}', 0) > 0

    @pytest.mark.parametrize("seed", range(4))
    def test_hostile_inputs_typed_or_identical(self, seed, tmp_path):
        """Seeded hostile-input sweep over the fused rung: adversarial level
        streams and value shapes either encode byte-identically to staged or
        raise the same typed error — and a path sink never commits a torn
        file either way (testing/faults.py's typed-or-identical contract,
        applied to the write side)."""
        rng = np.random.default_rng(seed)
        schema = parse_schema(
            "message m { required int64 a; optional binary s (UTF8); }"
        )
        n = int(rng.integers(1, 1200))
        dl = (rng.random(n) < rng.random()).astype(np.uint16)
        vals = [
            bytes(rng.integers(0, 256, int(rng.integers(0, 12))).astype(np.uint8))
            for _ in range(int(dl.sum()))
        ]
        hostile_dl = dl.copy()
        if seed % 2 and n > 3:
            hostile_dl[int(rng.integers(0, n))] = 7  # exceeds max_def
        a_col = rng.integers(0, 50, n).astype(np.int64)
        page_size = int(rng.integers(64, 4096))

        def write(path, use_dl):
            w = FileWriter(
                str(path), schema, codec="snappy", max_page_size=page_size
            )
            w.write_column("a", a_col)
            w.write_column("s", vals, def_levels=use_dl)
            w.flush_row_group()
            return w.close()

        for use_dl, tag in ((dl, "ok"), (hostile_dl, "hostile")):
            p_fused = tmp_path / f"fused_{tag}.parquet"
            p_staged = tmp_path / f"staged_{tag}.parquet"
            fused_err = staged_err = None
            try:
                write(p_fused, use_dl)
            except Exception as e:  # noqa: BLE001 — compared classwise below
                fused_err = e
            os.environ["PQT_FUSED_ENCODE"] = "0"
            try:
                write(p_staged, use_dl)
            except Exception as e:  # noqa: BLE001
                staged_err = e
            finally:
                del os.environ["PQT_FUSED_ENCODE"]
            if staged_err is None:
                assert fused_err is None
                assert p_fused.read_bytes() == p_staged.read_bytes()
            else:
                # both rungs fail with the SAME typed error, and the
                # destination is never committed (atomic sink)
                assert type(fused_err) is type(staged_err)
                assert not p_fused.exists()
                assert not p_staged.exists()
            assert _tmp_leftovers(tmp_path) == []

    @pytest.mark.slow
    def test_full_matrix_slow(self):
        """Extended fused-vs-staged sweep: every fused-eligible value route
        x codec x dpv x crc x page size, byte-identical or bust."""
        for codec in CODECS:
            for dpv in (1, 2):
                for crc in (False, True):
                    for mp in (512, 1 << 20):
                        self._differential(
                            self.MATRIX_SCHEMA,
                            self.MATRIX_COLS,
                            n_groups=2,
                            rows=1200,
                            codec=codec,
                            data_page_version=dpv,
                            with_crc=crc,
                            max_page_size=mp,
                            column_encodings={"ts": "DELTA_BINARY_PACKED"},
                        )

    def test_flaky_sink_under_fused_encoder(self, tmp_path):
        """FlakySink faults during fused-encoded writes: complete file or
        typed error and nothing committed (the PR 6 contract, re-pinned with
        the native rung producing the bytes)."""
        for seed in range(6):
            path = tmp_path / f"f{seed}.parquet"
            flaky = FlakySink(
                LocalFileSink(path), seed=seed, error_rate=0.2, permanent=True
            )
            try:
                _w = FileWriter(flaky, SCHEMA, codec="snappy")
                for g in range(3):
                    _w.write_column(
                        "id", np.arange(g * 200, (g + 1) * 200, dtype=np.int64)
                    )
                    _w.write_column("name", [f"n{i % 7}" for i in range(200)])
                    _w.write_column(
                        "x",
                        np.arange(200) * 0.25,
                        def_levels=np.ones(200, dtype=np.uint16),
                    )
                    _w.flush_row_group()
                _w.close()
                assert pq.read_table(str(path)).num_rows == 600
            except WriterError:
                assert not path.exists()
            assert _tmp_leftovers(tmp_path) == []


class TestFlakySinkFaults:
    """Flush failures surface as typed WriterError and NEVER corrupt
    committed output: the destination either holds the complete file or
    does not exist."""

    def test_serial_write_fault_is_typed_and_uncommitted(self, tmp_path):
        path = tmp_path / "f.parquet"
        # magic (4 bytes) succeeds; the first row-group flush fails
        sink = FlakySink(LocalFileSink(path), seed=3, fail_after_bytes=4)
        with pytest.raises(WriterError):
            with FileWriter(sink, SCHEMA) as w:
                w.write_column("id", np.arange(100, dtype=np.int64))
                w.write_column("name", ["a"] * 100)
                w.write_column("x", np.zeros(100))
                w.flush_row_group()
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []

    def test_fault_at_first_byte_is_typed(self, tmp_path):
        # even the constructor's magic write failing must be typed + clean
        path = tmp_path / "f.parquet"
        with pytest.raises(WriterError):
            FileWriter(FlakySink(LocalFileSink(path), permanent=True), SCHEMA)
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []

    def test_fail_after_bytes_mid_file(self, tmp_path):
        path = tmp_path / "f.parquet"
        sink = FlakySink(LocalFileSink(path), seed=5, fail_after_bytes=2000)
        with pytest.raises(WriterError):
            with FileWriter(sink, SCHEMA, codec="snappy") as w:
                for g in range(20):
                    w.write_column("id", np.arange(500, dtype=np.int64))
                    w.write_column("name", [f"n{i}" for i in range(500)])
                    w.write_column("x", np.arange(500) * 1.0)
                    w.flush_row_group()
        assert not path.exists()

    def test_commit_fault_leaves_no_file(self, tmp_path):
        # a caller-OWNED sink: the writer flushes, the CALLER commits; a
        # failing commit aborts the inner sink — no torn destination
        path = tmp_path / "f.parquet"
        sink = FlakySink(LocalFileSink(path), commit_error=True)
        w = _write_groups(sink, n_groups=1)
        w.close()  # writer done; the sink is still the caller's to commit
        with pytest.raises(OSError):
            sink.close()
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []

    def test_owned_path_commit_fault_is_writer_error(self, tmp_path, monkeypatch):
        # a writer-OWNED path sink whose commit rename fails: WriterError,
        # destination clean, close idempotent after the error
        path = tmp_path / "f.parquet"
        w = _write_groups(str(path), n_groups=1)

        def no_rename(src, dst):
            raise OSError("rename refused")

        monkeypatch.setattr(os, "replace", no_rename)
        with pytest.raises(WriterError):
            w.close()
        monkeypatch.undo()
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []
        assert w.close() is None  # idempotent after the error

    @pytest.mark.parametrize("seed", [1, 2, 3, 4])
    def test_transient_faults_seeded_sweep(self, tmp_path, seed):
        """Seeded storm: every outcome is either a complete, valid,
        byte-identical-to-clean file or a typed WriterError with nothing
        committed."""
        clean = MemorySink()
        _write_groups(clean, n_groups=4, codec="snappy").close()
        path = tmp_path / f"f{seed}.parquet"
        sink = FlakySink(LocalFileSink(path), seed=seed, error_rate=0.12)
        try:
            _write_groups(sink, n_groups=4, codec="snappy").close()
        except WriterError:
            assert not path.exists()
        else:
            assert path.read_bytes() == clean.getvalue()
        assert _tmp_leftovers(tmp_path) == []

    def test_parallel_deferred_error_is_typed(self, tmp_path):
        path = tmp_path / "f.parquet"
        sink = FlakySink(LocalFileSink(path), seed=9, fail_after_bytes=4)
        w = FileWriter(sink, SCHEMA, parallel=2)
        with pytest.raises(WriterError):
            # the fault happens on the background flusher; it must surface
            # as WriterError from a LATER writer call (deferred), at the
            # latest from close()
            for g in range(50):
                w.write_column("id", np.arange(100, dtype=np.int64))
                w.write_column("name", ["b"] * 100)
                w.write_column("x", np.ones(100))
                w.flush_row_group()
            w.close()
        assert w.close() is None  # idempotent after error
        assert not path.exists()

    def test_background_fault_after_last_call_raises_from_close(self, tmp_path):
        """A pipeline fault that lands AFTER the caller's last write call
        must still raise from close() — a `with` block exiting cleanly
        while the destination silently never appears would be the worst
        failure mode of deferred propagation."""
        import time

        path = tmp_path / "f.parquet"
        sink = FlakySink(LocalFileSink(path), fail_after_bytes=4)
        w = FileWriter(sink, SCHEMA, parallel=2)
        w.write_column("id", np.arange(100, dtype=np.int64))
        w.write_column("name", ["c"] * 100)
        w.write_column("x", np.ones(100))
        try:
            w.flush_row_group()  # submit; the background flush will fail
        except WriterError:
            pytest.skip("fault surfaced synchronously; race not exercised")
        time.sleep(0.3)  # let the flusher hit the fault with no call pending
        with pytest.raises(WriterError):
            w.close()
        assert w.close() is None  # idempotent after the raise
        assert not path.exists()

    def test_writer_unusable_after_failure(self):
        sink = FlakySink(MemorySink(), fail_after_bytes=4)
        w = FileWriter(sink, SCHEMA)
        with pytest.raises(WriterError):
            w.write_column("id", np.arange(10, dtype=np.int64))
            w.write_column("name", ["x"] * 10)
            w.write_column("x", np.zeros(10))
            w.flush_row_group()
        with pytest.raises(WriterError):
            w.write_row({"id": 1, "name": "y"})

    def test_serial_encode_error_never_commits_partial_file(self, tmp_path):
        """An ENCODE fault (bad values, not a sink fault) after a good
        group: the group's buffers are already consumed, so a later close()
        must not commit a valid-looking file with that group silently
        missing — the writer poisons and the destination stays absent."""
        path = tmp_path / "f.parquet"
        w = FileWriter(str(path), SCHEMA)
        w.write_column("id", np.arange(10, dtype=np.int64))
        w.write_column("name", ["ok"] * 10)
        w.write_column("x", np.zeros(10))
        w.flush_row_group()
        w.write_column("id", ["not", "an", "int"])  # fails at encode time
        w.write_column("name", ["a", "b", "c"])
        w.write_column("x", np.zeros(3))
        with pytest.raises(ValueError):  # WriterError wrapping StoreError
            w.flush_row_group()
        assert w.close() is None  # no commit after the poison
        assert not path.exists()
        assert _tmp_leftovers(tmp_path) == []


class TestBackpressureAndMetrics:
    def test_tiny_inflight_budget_still_correct(self):
        serial = MemorySink()
        _write_groups(serial, n_groups=8, codec="snappy").close()
        par = MemorySink()
        _write_groups(
            par, n_groups=8, codec="snappy", parallel=2, max_inflight_bytes=1
        ).close()
        assert par.getvalue() == serial.getvalue()

    def test_write_metric_families(self):
        before = metrics.snapshot()
        sink = MemorySink()
        _write_groups(sink, n_groups=2, codec="snappy").close()
        d = metrics.delta(before)
        assert sum(
            v for k, v in d.items() if k.startswith("pages_written_total")
        ) > 0
        assert d.get('write_bytes_total{codec="SNAPPY"}', 0) > 0
        assert d.get("encode_seconds_count", 0) >= 6  # 2 groups x 3 chunks
        assert d.get("sink_bytes_written_total", 0) > 0

    def test_write_trace_stages(self):
        from parquet_tpu.utils.trace import decode_trace

        sink = MemorySink()
        with decode_trace() as tr:
            _write_groups(sink, n_groups=2, codec="snappy").close()
        assert tr.stages["write.encode"].calls == 6
        assert tr.stages["write.flush"].calls == 2
        assert tr.stages["write.flush"].bytes > 0


class TestHighLevelPassthrough:
    def test_floor_writer_sink_and_parallel(self, tmp_path):
        import dataclasses

        from parquet_tpu import floor

        @dataclasses.dataclass
        class Rec:
            id: int
            name: str

        sink = MemorySink()
        with floor.Writer(sink, Rec, parallel=2) as w:
            w.write_all(Rec(i, f"n{i % 5}") for i in range(100))
        got = pq.read_table(io.BytesIO(sink.getvalue()))
        assert got.num_rows == 100
        # and a path commits atomically through floor too
        path = tmp_path / "floor.parquet"
        with pytest.raises(RuntimeError):
            with floor.Writer(str(path), Rec) as w:
                w.write(Rec(1, "a"))
                raise RuntimeError("boom")
        assert not path.exists()

    def test_csv2parquet_parallel_flag(self, tmp_path):
        from parquet_tpu.tools.csv2parquet import main as csv_main

        src = tmp_path / "in.csv"
        src.write_text(
            "id,score\n" + "\n".join(f"{i},{i / 2}" for i in range(200)) + "\n"
        )
        out = tmp_path / "out.parquet"
        rc = csv_main(
            [
                "-o", str(out), "-typehints", "id=int64,score=double",
                "--parallel", "2", str(src),
            ]
        )
        assert rc == 0
        assert pq.read_table(str(out)).num_rows == 200

    def test_merge_goes_through_sink(self, tmp_path, monkeypatch):
        from parquet_tpu.core import merge as merge_mod
        from parquet_tpu.core.merge import merge_files

        p1 = str(tmp_path / "a.parquet")
        _write_groups(p1, n_groups=2).close()
        out = str(tmp_path / "m.parquet")
        merge_files(out, [p1, p1])
        assert pq.read_table(out).num_rows == 2000  # 2 x (2 groups x 500)
        assert _tmp_leftovers(tmp_path) == []
        # a failure mid-copy aborts the sink: no torn output appears
        real = merge_mod._copy_group

        def exploding(out_f, pos, f, rg, ordinal, label):
            if ordinal >= 1:
                raise OSError("disk gone")
            return real(out_f, pos, f, rg, ordinal, label)

        monkeypatch.setattr(merge_mod, "_copy_group", exploding)
        out2 = str(tmp_path / "m2.parquet")
        with pytest.raises(OSError):
            merge_files(out2, [p1, p1])
        assert not os.path.exists(out2)
        assert _tmp_leftovers(tmp_path) == []

"""Thrift compact protocol + footer metadata tests.

Oracle: pyarrow-written files (cross-implementation, like the reference's
parquet-mr compatibility harness, reference: compatibility/run_tests.bash).
"""

import io

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.meta import (
    CompactReader,
    CompactWriter,
    Encoding,
    FileMetaData,
    ParquetFileError,
    SchemaElement,
    Statistics,
    Type,
    read_file_metadata,
    serialize_footer,
)
from parquet_tpu.meta.thrift import ThriftError


def _pa_file(table, **kw) -> io.BytesIO:
    buf = io.BytesIO()
    pq.write_table(table, buf, **kw)
    buf.seek(0)
    return buf


class TestVarints:
    def test_uvarint_roundtrip(self):
        for v in [0, 1, 127, 128, 300, 2**31, 2**63 - 1, 2**64 - 1]:
            w = CompactWriter()
            w.write_uvarint(v)
            r = CompactReader(w.getvalue())
            assert r.read_uvarint() == v

    def test_zigzag_roundtrip(self):
        for v in [0, -1, 1, -64, 63, 2**31 - 1, -(2**31), 2**63 - 1, -(2**63)]:
            w = CompactWriter()
            w.write_zigzag(v)
            r = CompactReader(w.getvalue())
            assert r.read_zigzag() == v

    def test_truncated_varint_raises(self):
        with pytest.raises(ThriftError):
            CompactReader(b"\x80\x80").read_uvarint()


class TestStructRoundtrip:
    def test_schema_element(self):
        se = SchemaElement(type=int(Type.INT64), name="col", repetition_type=1, num_children=None)
        se2 = SchemaElement.loads(se.dumps())
        assert se2.type == int(Type.INT64)
        assert se2.name == "col"
        assert se2.repetition_type == 1
        assert se2.num_children is None

    def test_statistics_binary(self):
        st = Statistics(min_value=b"\x00\x01", max_value=b"\xff\xfe", null_count=3)
        st2 = Statistics.loads(st.dumps())
        assert st2.min_value == b"\x00\x01"
        assert st2.max_value == b"\xff\xfe"
        assert st2.null_count == 3

    def test_unknown_fields_skipped(self):
        # A struct with an extra field id 200 must parse (forward compat).
        w = CompactWriter()
        w.write_byte(0x15)  # field 1, i32
        w.write_zigzag(42)
        w.write_byte(0x05)  # long-form field header, i32
        w.write_zigzag(200)
        w.write_zigzag(7)
        w.write_byte(0x00)
        se = SchemaElement.loads(w.getvalue())
        assert se.type == 42

    def test_large_field_id_delta(self):
        st = Statistics(null_count=5)  # field 3 written with delta 3
        data = st.dumps()
        assert Statistics.loads(data).null_count == 5


class TestFooter:
    def test_pyarrow_footer_parses(self):
        t = pa.table(
            {
                "i64": pa.array([1, 2, None], pa.int64()),
                "f64": pa.array([1.5, 2.5, 3.5]),
                "s": pa.array(["a", "bb", "ccc"]),
                "b": pa.array([True, False, None]),
            }
        )
        m = read_file_metadata(_pa_file(t, compression="snappy"))
        assert m.num_rows == 3
        leaf_types = {
            tuple(c.meta_data.path_in_schema): Type(c.meta_data.type)
            for c in m.row_groups[0].columns
        }
        assert leaf_types[("i64",)] == Type.INT64
        assert leaf_types[("f64",)] == Type.DOUBLE
        assert leaf_types[("s",)] == Type.BYTE_ARRAY
        assert leaf_types[("b",)] == Type.BOOLEAN

    def test_nested_schema_parses(self):
        t = pa.table({"l": pa.array([[1, 2], None, [3]], pa.list_(pa.int32()))})
        m = read_file_metadata(_pa_file(t))
        names = [se.name for se in m.schema]
        assert "l" in names
        assert any(se.num_children for se in m.schema[1:])

    def test_footer_reserialize_reparses(self):
        t = pa.table({"x": pa.array(range(100), pa.int64())})
        m = read_file_metadata(_pa_file(t))
        m2 = FileMetaData.loads(m.dumps())
        assert m2.num_rows == m.num_rows
        assert [se.name for se in m2.schema] == [se.name for se in m.schema]
        c = m.row_groups[0].columns[0].meta_data
        c2 = m2.row_groups[0].columns[0].meta_data
        assert c2.data_page_offset == c.data_page_offset
        assert c2.encodings == c.encodings

    def test_serialize_footer_shape(self):
        m = FileMetaData(
            version=1,
            schema=[SchemaElement(name="root", num_children=0)],
            num_rows=0,
            row_groups=[],
        )
        raw = serialize_footer(m)
        assert raw.endswith(b"PAR1")
        f = io.BytesIO(b"PAR1" + raw)
        m2 = read_file_metadata(f)
        assert m2.num_rows == 0

    def test_bad_magic_rejected(self):
        with pytest.raises(ParquetFileError):
            read_file_metadata(io.BytesIO(b"NOPE" + b"\x00" * 16 + b"NOPE"))

    def test_too_small_rejected(self):
        with pytest.raises(ParquetFileError):
            read_file_metadata(io.BytesIO(b"PAR1PAR1"))

    def test_bad_footer_length_rejected(self):
        bad = b"PAR1" + b"\x00" * 8 + b"\xff\xff\xff\x7f" + b"PAR1"
        with pytest.raises(ParquetFileError):
            read_file_metadata(io.BytesIO(bad))


class TestEnums:
    def test_encoding_values_match_spec(self):
        assert Encoding.PLAIN == 0
        assert Encoding.RLE == 3
        assert Encoding.DELTA_BINARY_PACKED == 5
        assert Encoding.RLE_DICTIONARY == 8

"""PR 18: the mesh telemetry plane — propagation, federation, SLO.

Pinned here:
  * traceparent hygiene: parse/mint reject malformed, forbidden-version
    and all-zero headers; inbound resolution ADOPTS a valid trace-id but
    always mints a fresh span-id (the daemon is a new span, not the
    caller's);
  * end-to-end propagation: a client traceparent sent to the daemon rides
    every remote-map range GET to the object store (httpstub records the
    received headers — same trace-id, never the client's span-id), comes
    back on the response and in typed error bodies, lands in the flight
    recorder and in the exported Chrome trace's otherData — and
    `parquet-tool trace-merge` stitches two processes' trace documents
    into ONE Perfetto timeline on that shared trace-id;
  * federation exactness: merged counters are byte-for-byte the
    arithmetic sum of the replica lines (integers stay integers),
    histogram buckets/sum/count add per label set, gauges are NOT summed
    (each replica keeps its sample under a replica= label), and a family
    typed differently across replicas refuses to merge;
  * SLO burn-rate: on a fake clock, an injected fault schedule drives
    ok -> burning -> ok; while burning, /healthz reports "degraded" at
    HTTP 200 (routable, deprioritized — distinct from draining's 503)
    and new scans still complete;
  * exposition goldens: every new family (io_traceparent_*, fleet_*,
    slo_*, process_*) renders with HELP + TYPE in classic Prometheus and
    OpenMetrics;
  * lane audit: every pqt-* worker pool the codebase spawns attributes to
    a named profiler lane, never "other".
"""

from __future__ import annotations

import http.client
import json
import re
import threading
import time
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.obs import fleet, propagate
from parquet_tpu.obs.prof import lane_of
from parquet_tpu.obs.slo import BurnRateEngine, SLOObjective
from parquet_tpu.serve import ScanServer, ServeConfig
from parquet_tpu.testing.httpstub import RangeHttpStub
from parquet_tpu.tools.parquet_tool import main as tool_main
from parquet_tpu.utils import metrics

WATCHDOG_S = 30.0

ROWS = 1600
ROW_GROUP = 400


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("mesh_corpus")
    t = pa.table(
        {
            "id": pa.array(np.arange(ROWS, dtype=np.int64)),
            "v": pa.array(np.linspace(0.0, 1.0, ROWS)),
        }
    )
    pq.write_table(t, str(d / "a.parquet"), row_group_size=ROW_GROUP)
    return d


def _request(server, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection(
        server.host, server.port, timeout=WATCHDOG_S
    )
    try:
        conn.request(
            method,
            path,
            body=json.dumps(body).encode() if body is not None else None,
            headers=headers or {},
        )
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


# -- traceparent hygiene -------------------------------------------------------


class TestTraceparent:
    def test_mint_parse_round_trip(self):
        ctx = propagate.mint()
        parsed = propagate.parse_traceparent(ctx.header())
        assert parsed is not None
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id

    def test_header_shape(self):
        h = propagate.mint().header()
        assert re.fullmatch(r"00-[0-9a-f]{32}-[0-9a-f]{16}-[0-9a-f]{2}", h)

    def test_child_keeps_trace_id_fresh_span(self):
        ctx = propagate.mint()
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id

    @pytest.mark.parametrize(
        "raw",
        [
            "",
            "not-a-header",
            "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace
            "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span
            "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",  # forbidden version
            "00-" + "A" * 32 + "-" + "b" * 16 + "-01",  # uppercase hex
            "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace-id
            "00-" + "a" * 32 + "-" + "b" * 16 + "-01-extra" + "x" * 200,
        ],
    )
    def test_parse_rejects(self, raw):
        assert propagate.parse_traceparent(raw) is None

    def test_future_version_accepted(self):
        # per W3C: unknown (non-ff) versions parse on the 00 grammar
        got = propagate.parse_traceparent(
            "01-" + "a" * 32 + "-" + "b" * 16 + "-01"
        )
        assert got is not None and got.trace_id == "a" * 32

    def test_resolve_inbound_adopts_trace_id_mints_span(self):
        raw = "00-" + "ab" * 16 + "-" + "12" * 8 + "-01"
        ctx, outcome = propagate.resolve_inbound(raw)
        assert outcome == "accepted"
        assert ctx.trace_id == "ab" * 16
        assert ctx.span_id != "12" * 8  # the daemon is a NEW span

    def test_resolve_inbound_mints_on_absent_and_invalid(self):
        for raw, outcome in ((None, "minted"), ("garbage", "invalid")):
            ctx, got = propagate.resolve_inbound(raw)
            assert got == outcome
            assert propagate.parse_traceparent(ctx.header()) is not None

    def test_outbound_requires_scope(self):
        assert propagate.outbound_traceparent("get") is None
        ctx = propagate.mint()
        with propagate.propagation_scope(ctx):
            h = propagate.outbound_traceparent("get")
            assert h is not None
            sent = propagate.parse_traceparent(h)
            assert sent.trace_id == ctx.trace_id
            assert sent.span_id != ctx.span_id  # fresh child per call
        assert propagate.outbound_traceparent("get") is None


# -- trace-merge ---------------------------------------------------------------


def _doc(trace_id, endpoint, pid=9):
    return {
        "traceEvents": [
            {"ph": "X", "name": "s", "pid": pid, "tid": 1, "ts": 0, "dur": 2}
        ],
        "otherData": {
            "propagation": {"trace_id": trace_id},
            "request": {"endpoint": endpoint},
        },
    }


class TestTraceMerge:
    def test_merges_on_shared_trace_id(self):
        tid = "ab" * 16
        merged = propagate.merge_chrome_traces(
            [_doc(tid, "scan"), _doc(tid, "put")]
        )
        assert merged["otherData"]["propagation"]["trace_id"] == tid
        names = [
            e["args"]["name"]
            for e in merged["traceEvents"]
            if e.get("name") == "process_name"
        ]
        assert names == ["scan", "put"]
        # each input got its own pid lane
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}

    def test_refuses_distinct_trace_ids(self):
        with pytest.raises(ValueError, match="distinct trace ids"):
            propagate.merge_chrome_traces(
                [_doc("ab" * 16, "a"), _doc("cd" * 16, "b")]
            )

    def test_cli_round_trip(self, tmp_path):
        tid = "ef" * 16
        pa_, pb, po = (
            tmp_path / "a.json",
            tmp_path / "b.json",
            tmp_path / "m.json",
        )
        pa_.write_text(json.dumps(_doc(tid, "scan")))
        pb.write_text(json.dumps(_doc(tid, "remote")))
        rc = tool_main(["trace-merge", str(pa_), str(pb), "-o", str(po)])
        assert rc == 0
        merged = json.loads(po.read_text())
        assert merged["otherData"]["propagation"]["trace_id"] == tid
        assert len(merged["traceEvents"]) == 4  # 2 spans + 2 process names

    def test_cli_label_count_mismatch_fails(self, tmp_path, capsys):
        p = tmp_path / "a.json"
        p.write_text(json.dumps(_doc("ab" * 16, "scan")))
        rc = tool_main(["trace-merge", str(p), "--label", "x", "--label", "y"])
        assert rc == 1
        assert "one --label per input" in capsys.readouterr().err


# -- federation exactness ------------------------------------------------------

_REP_A = """\
# HELP parquet_tpu_demo_total demo counter
# TYPE parquet_tpu_demo_total counter
parquet_tpu_demo_total{op="read"} 3
parquet_tpu_demo_total{op="write"} 10
# TYPE parquet_tpu_up gauge
parquet_tpu_up 1
# TYPE parquet_tpu_lat_seconds histogram
parquet_tpu_lat_seconds_bucket{le="0.1"} 2
parquet_tpu_lat_seconds_bucket{le="+Inf"} 3
parquet_tpu_lat_seconds_sum 0.5
parquet_tpu_lat_seconds_count 3
"""

_REP_B = """\
# TYPE parquet_tpu_demo_total counter
parquet_tpu_demo_total{op="read"} 4
# TYPE parquet_tpu_up gauge
parquet_tpu_up 1
# TYPE parquet_tpu_lat_seconds histogram
parquet_tpu_lat_seconds_bucket{le="0.1"} 5
parquet_tpu_lat_seconds_bucket{le="+Inf"} 6
parquet_tpu_lat_seconds_sum 1.25
parquet_tpu_lat_seconds_count 6
"""


class TestFederationExactness:
    def test_counters_sum_byte_for_byte(self):
        merged = fleet.merge_expositions([_REP_A, _REP_B], ["r1", "r2"])
        # integer counters stay integers: 3+4=7 rendered exactly
        assert 'parquet_tpu_demo_total{op="read"} 7\n' in merged
        # a sample present on only one replica passes through unchanged
        assert 'parquet_tpu_demo_total{op="write"} 10\n' in merged

    def test_histogram_buckets_add(self):
        merged = fleet.merge_expositions([_REP_A, _REP_B], ["r1", "r2"])
        assert 'parquet_tpu_lat_seconds_bucket{le="0.1"} 7\n' in merged
        assert 'parquet_tpu_lat_seconds_bucket{le="+Inf"} 9\n' in merged
        assert "parquet_tpu_lat_seconds_sum 1.75\n" in merged
        assert "parquet_tpu_lat_seconds_count 9\n" in merged

    def test_gauges_keep_replica_label_not_summed(self):
        merged = fleet.merge_expositions([_REP_A, _REP_B], ["r1", "r2"])
        assert 'parquet_tpu_up{replica="r1"} 1\n' in merged
        assert 'parquet_tpu_up{replica="r2"} 1\n' in merged
        assert "parquet_tpu_up 2" not in merged

    def test_type_skew_refuses_to_merge(self):
        skew = _REP_B.replace(
            "# TYPE parquet_tpu_up gauge", "# TYPE parquet_tpu_up counter"
        )
        with pytest.raises(ValueError, match="deploy skew"):
            fleet.merge_expositions([_REP_A, skew], ["r1", "r2"])

    def test_merge_is_deterministic(self):
        one = fleet.merge_expositions([_REP_A, _REP_B], ["r1", "r2"])
        two = fleet.merge_expositions([_REP_A, _REP_B], ["r1", "r2"])
        assert one == two

    def test_own_render_parses_and_remerges(self):
        # the registry's own classic render (HELP before TYPE) must parse,
        # and a 2-replica self-merge must double every counter exactly
        metrics.inc("pqt_mesh_selfmerge_total", 3, op="x")
        text = metrics.render_prometheus()
        fams = fleet.parse_exposition(text)
        key = "parquet_tpu_pqt_mesh_selfmerge_total"
        assert fams[key].kind == "counter"
        merged = fleet.merge_expositions([text, text], ["r1", "r2"])
        assert 'parquet_tpu_pqt_mesh_selfmerge_total{op="x"} 6\n' in merged

    def test_normalize_peer(self):
        assert fleet.normalize_peer("127.0.0.1:8080") == (
            "http://127.0.0.1:8080/metrics"
        )
        assert fleet.normalize_peer("http://h:1/metrics") == (
            "http://h:1/metrics"
        )
        assert fleet.normalize_peer("https://h:1/") == "https://h:1/metrics"


# -- exposition goldens for the new families -----------------------------------


class TestMeshGoldens:
    def test_new_families_render_with_help_and_type(self):
        # exercise each family once so it exists in the registry
        ctx, _ = propagate.resolve_inbound(None)
        with propagate.propagation_scope(ctx):
            propagate.outbound_traceparent("get")
        BurnRateEngine(SLOObjective()).evaluate()
        fleet.federate(
            ["http://r1/metrics"], fetch=lambda url, t: _REP_A
        )
        classic = metrics.render_prometheus()
        om = metrics.render_openmetrics()
        for family, kind in [
            ("io_traceparent_injected_total", "counter"),
            ("io_traceparent_inbound_total", "counter"),
            ("fleet_scrapes_total", "counter"),
            ("fleet_replicas", "gauge"),
            ("slo_burn_rate", "gauge"),
            ("slo_error_budget_remaining", "gauge"),
            ("slo_verdict", "gauge"),
        ]:
            name = f"parquet_tpu_{family}"
            assert f"# HELP {name} " in classic, family
            assert f"# TYPE {name} {kind}" in classic, family
            om_name = (
                name[: -len("_total")]
                if kind == "counter" and name.endswith("_total")
                else name
            )
            assert f"# TYPE {om_name} {kind}" in om, family

    def test_process_self_metrics_refresh_at_render(self):
        stats = metrics.process_stats()
        text = metrics.render_prometheus()
        for family, key in [
            ("process_resident_memory_bytes", "rss_bytes"),
            ("process_open_fds", "open_fds"),
            ("process_threads_total", "threads"),
        ]:
            if key not in stats:
                continue  # non-Linux: the gauge is simply absent
            name = f"parquet_tpu_{family}"
            assert f"# TYPE {name} gauge" in text, family
            m = re.search(rf"^{re.escape(name)} (\S+)$", text, re.M)
            assert m is not None and float(m.group(1)) > 0, family

    def test_process_stats_threads_always_present(self):
        # /proc may be missing; threading.active_count() never is
        assert metrics.process_stats()["threads"] >= 1


# -- the burn-rate engine on a fake clock --------------------------------------


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestBurnRateEngine:
    def test_quiet_engine_is_ok(self):
        eng = BurnRateEngine(SLOObjective(), clock=_Clock())
        v = eng.evaluate()
        assert v["verdict"] == "ok"
        assert set(v["windows"]) == {"5m", "1h"}

    def test_fault_schedule_ok_burning_ok(self):
        clock = _Clock()
        eng = BurnRateEngine(
            SLOObjective(availability=0.99), clock=clock
        )
        for _ in range(100):
            eng.record(200, 0.005)
        assert eng.evaluate()["verdict"] == "ok"
        # 50% errors: burn 50x on BOTH windows (page bar is 14.4)
        for _ in range(100):
            eng.record(500, 0.005)
        v = eng.evaluate()
        assert v["verdict"] == "burning"
        assert v["burn_rates"]["availability"]["5m"] >= 14.4
        assert v["burn_rates"]["availability"]["1h"] >= 14.4
        # the schedule ends; once the slow window rolls past the burst,
        # the verdict recovers without any reset call
        clock.t += 3700.0
        for _ in range(50):
            eng.record(200, 0.005)
        assert eng.evaluate()["verdict"] == "ok"

    def test_fast_only_burn_is_warn_not_page(self):
        clock = _Clock()
        eng = BurnRateEngine(SLOObjective(availability=0.99), clock=clock)
        # seed a long clean hour so the slow window stays under the bar
        for _ in range(36):
            for _ in range(100):
                eng.record(200, 0.001)
            clock.t += 100.0
        # a short 5% burst: the fast window (300 clean + 100 here) burns
        # at 1.25x, the hour window at ~0.14x — warn territory, no page
        for _ in range(95):
            eng.record(200, 0.001)
        for _ in range(5):
            eng.record(500, 0.001)
        v = eng.evaluate()
        assert v["verdict"] == "warn"
        assert v["burn_rates"]["availability"]["5m"] >= 1.0
        assert v["burn_rates"]["availability"]["1h"] < 14.4

    def test_latency_sli_burns_when_p99_objective_set(self):
        eng = BurnRateEngine(
            SLOObjective(availability=0.999, p99_ms=10.0), clock=_Clock()
        )
        for _ in range(100):
            eng.record(200, 0.050)  # 50 ms: every request over the bar
        v = eng.evaluate()
        assert v["verdict"] == "burning"
        assert v["burn_rates"]["latency"]["5m"] >= 14.4
        assert v["windows"]["5m"]["p99_ms_estimate"] >= 10.0

    def test_no_latency_sli_without_objective(self):
        eng = BurnRateEngine(SLOObjective(), clock=_Clock())
        eng.record(200, 0.001)
        assert "latency" not in eng.evaluate()["burn_rates"]

    def test_error_status_string_counts_as_bad(self):
        eng = BurnRateEngine(SLOObjective(availability=0.99), clock=_Clock())
        for _ in range(10):
            eng.record("error", 0.001)
        assert eng.evaluate()["verdict"] == "burning"

    def test_client_errors_spend_no_budget(self):
        eng = BurnRateEngine(SLOObjective(availability=0.99), clock=_Clock())
        for _ in range(100):
            eng.record(404, 0.001)
        v = eng.evaluate()
        assert v["verdict"] == "ok"
        assert v["windows"]["5m"]["errors"] == 0

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            SLOObjective(availability=1.5)
        with pytest.raises(ValueError):
            SLOObjective(p99_ms=-1.0)
        with pytest.raises(ValueError):
            SLOObjective(fast_window_s=600.0, slow_window_s=300.0)


# -- the daemon under the SLO engine (seeded chaos) ----------------------------


class TestServeSLO:
    def test_healthz_degrades_at_200_while_burning(self, corpus):
        clock = _Clock()
        eng = BurnRateEngine(SLOObjective(availability=0.99), clock=clock)
        with ScanServer(
            ServeConfig(port=0, root=str(corpus), slo_engine=eng)
        ) as server:
            server.start_background()
            status, _, body = _request(server, "GET", "/healthz")
            assert status == 200 and json.loads(body)["status"] == "ok"
            # the injected fault schedule: a 50% 5xx burst
            for _ in range(50):
                eng.record(200, 0.01)
                eng.record(503, 0.01)
            status, _, body = _request(server, "GET", "/healthz")
            doc = json.loads(body)
            # degraded is ROUTABLE: 200, not draining's 503
            assert status == 200
            assert doc["status"] == "degraded" and doc["slo"] == "burning"
            # new scans still complete while burning
            status, _, body = _request(
                server, "POST", "/v1/scan", {"paths": "a.parquet", "limit": 3}
            )
            assert status == 200 and body.count(b"\n") == 3
            # schedule over + windows rolled: the daemon recovers
            clock.t += 3700.0
            status, _, body = _request(server, "GET", "/healthz")
            assert status == 200 and json.loads(body)["status"] == "ok"

    def test_debug_slo_endpoint_shape(self, corpus):
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus),
                slo_availability=0.99, slo_p99_ms=250.0,
            )
        ) as server:
            server.start_background()
            # real traffic feeds the engine through _finish
            status, _, body = _request(
                server, "POST", "/v1/scan", {"paths": "a.parquet"}
            )
            assert status == 200, body
            # _finish runs after the response bytes flush: poll until the
            # sample lands rather than racing the handler thread
            deadline = time.time() + WATCHDOG_S
            while True:
                status, _, body = _request(server, "GET", "/v1/debug/slo")
                assert status == 200
                doc = json.loads(body)
                if doc["windows"]["5m"]["requests"] >= 1:
                    break
                assert time.time() < deadline, doc
                time.sleep(0.01)
            assert doc["verdict"] in ("ok", "warn", "burning")
            assert doc["objective"]["availability"] == 0.99
            assert doc["objective"]["p99_ms"] == 250.0
            assert doc["windows"]["5m"]["requests"] >= 1
            assert set(doc["burn_rates"]) == {"availability", "latency"}
            # the objective also rides /v1/debug/vars
            status, _, body = _request(server, "GET", "/v1/debug/vars")
            doc = json.loads(body)
            assert doc["slo"]["availability"] == 0.99
            assert doc["process"]["threads"] >= 1

    def test_bad_objective_rejected_at_config(self):
        with pytest.raises(ValueError, match="availability"):
            ServeConfig(port=0, slo_availability=2.0)


# -- end-to-end propagation ----------------------------------------------------


_CLIENT_TP = "00-" + "cafe" * 8 + "-" + "ab" * 8 + "-01"
_CLIENT_TID = "cafe" * 8


class TestServePropagation:
    def _remote_server(self, stub, corpus):
        return ScanServer(
            ServeConfig(
                port=0,
                root=str(corpus),
                remote_map={"warm": stub.base_url},
                trace_sample_rate=1.0,  # keep every span tree
            )
        )

    def test_traceparent_rides_remote_gets_and_response(self, corpus):
        data = (corpus / "a.parquet").read_bytes()
        with RangeHttpStub(files={"a.parquet": data}) as stub:
            with self._remote_server(stub, corpus) as server:
                server.start_background()
                status, headers, body = _request(
                    server,
                    "POST",
                    "/v1/scan",
                    {"paths": "warm/a.parquet", "columns": ["id"]},
                    headers={"traceparent": _CLIENT_TP},
                )
                assert status == 200, body
                # the response echoes the daemon's span on OUR trace
                echoed = propagate.parse_traceparent(headers["traceparent"])
                assert echoed.trace_id == _CLIENT_TID
                assert echoed.span_id != "ab" * 8
                # every range GET the stub served carried the trace-id,
                # each with a FRESH child span-id
                assert stub.traceparents, "no traceparent reached the stub"
                spans = set()
                for raw in stub.traceparents:
                    got = propagate.parse_traceparent(raw)
                    assert got is not None, raw
                    assert got.trace_id == _CLIENT_TID
                    assert got.span_id != "ab" * 8
                    spans.add(got.span_id)
                assert len(spans) == len(stub.traceparents)
                rid = headers["X-Request-Id"]
                status, _, body = _request(
                    server, "GET", f"/v1/debug/requests/{rid}"
                )
                assert json.loads(body)["trace_id"] == _CLIENT_TID

    def test_error_body_carries_trace_id(self, corpus):
        with ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ) as server:
            server.start_background()
            status, _, body = _request(
                server,
                "POST",
                "/v1/scan",
                {"paths": "../escape.parquet"},
                headers={"traceparent": _CLIENT_TP},
            )
            assert status == 403
            assert json.loads(body)["error"]["trace_id"] == _CLIENT_TID

    def test_invalid_inbound_header_is_replaced_never_echoed(self, corpus):
        with ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ) as server:
            server.start_background()
            evil = "00-" + "zz" * 16 + "-" + "ab" * 8 + "-01\r\nX-Inject: 1"
            status, headers, _ = _request(
                server,
                "POST",
                "/v1/scan",
                {"paths": "a.parquet", "limit": 1},
                headers={"traceparent": evil.replace("\r\n", " ")},
            )
            assert status == 200
            minted = propagate.parse_traceparent(headers["traceparent"])
            assert minted is not None
            assert minted.trace_id != "zz" * 16
            assert "X-Inject" not in headers

    def test_two_process_trace_merge_round_trip(self, corpus, tmp_path):
        """The acceptance pin: one client trace-id through two daemons,
        each exported Chrome trace carries it, and trace-merge stitches
        them into one document on the shared id."""
        data = (corpus / "a.parquet").read_bytes()
        docs = []
        with RangeHttpStub(files={"a.parquet": data}) as stub:
            for _ in range(2):
                with self._remote_server(stub, corpus) as server:
                    server.start_background()
                    status, headers, _ = _request(
                        server,
                        "POST",
                        "/v1/scan",
                        {"paths": "warm/a.parquet", "limit": 5},
                        headers={"traceparent": _CLIENT_TP},
                    )
                    assert status == 200
                    rid = headers["X-Request-Id"]
                    status, _, body = _request(
                        server, "GET", f"/v1/debug/requests/{rid}/trace"
                    )
                    assert status == 200, body
                    doc = json.loads(body)
                    assert (
                        doc["otherData"]["propagation"]["trace_id"]
                        == _CLIENT_TID
                    )
                    docs.append(doc)
        pa_, pb = tmp_path / "p0.json", tmp_path / "p1.json"
        po = tmp_path / "merged.json"
        pa_.write_text(json.dumps(docs[0]))
        pb.write_text(json.dumps(docs[1]))
        rc = tool_main(["trace-merge", str(pa_), str(pb), "-o", str(po)])
        assert rc == 0
        merged = json.loads(po.read_text())
        assert merged["otherData"]["propagation"]["trace_id"] == _CLIENT_TID
        # both processes' remote.get spans sit on the one timeline
        lanes = {e["pid"] for e in merged["traceEvents"]}
        assert lanes == {0, 1}
        names = {e.get("name") for e in merged["traceEvents"]}
        assert "remote.get" in names


# -- fleet federation over live daemons ----------------------------------------


class TestServeFleet:
    def test_fleet_smoke_two_daemons(self, corpus, tmp_path):
        """The make fleet-smoke pin: two daemons -> federated scrape via
        HTTP endpoint AND CLI -> the merged counters equal the arithmetic
        sum of the per-replica scrapes."""
        with ScanServer(ServeConfig(port=0, root=str(corpus))) as s1:
            s1.start_background()
            with ScanServer(ServeConfig(port=0, root=str(corpus))) as s2:
                s2.start_background()
                for s in (s1, s2):
                    _request(s, "POST", "/v1/scan", {"paths": "a.parquet"})
                peers = f"{s1.host}:{s1.port},{s2.host}:{s2.port}"
                texts = [
                    _request(s, "GET", "/metrics")[2].decode()
                    for s in (s1, s2)
                ]
                status, headers, body = _request(
                    s1, "GET", f"/v1/debug/fleet?peers={peers}"
                )
                assert status == 200, body
                assert headers["Content-Type"].startswith("text/plain")
                merged = body.decode()
                assert "# fleet: merged 2 replica(s)" in merged
                # exactness against the per-replica scrapes we hold
                key = re.escape(
                    'parquet_tpu_serve_requests_total{status="200",'
                    'tenant="default"}'
                )
                vals = [
                    int(re.search(rf"^{key} (\d+)$", t, re.M).group(1))
                    for t in texts
                ]
                m = re.search(rf"^{key} (\d+)$", merged, re.M)
                assert m is not None
                # scrapes raced the /metrics fetches above: the merged sum
                # can only be >= what we observed beforehand
                assert int(m.group(1)) >= sum(vals) > 0
                # gauges carry the replica label instead of summing: the
                # always-rendered uptime gauge appears once per replica
                uptimes = re.findall(
                    r'parquet_tpu_process_uptime_seconds\{replica="([^"]+)"\}',
                    merged,
                )
                assert len(uptimes) == 2 and len(set(uptimes)) == 2
        # the CLI federates the same way (daemons now closed: error path)
        rc = tool_main(["debug", "--fleet", "127.0.0.1:1"])
        assert rc == 1

    def test_fleet_endpoint_typed_errors(self, corpus):
        with ScanServer(ServeConfig(port=0, root=str(corpus))) as server:
            server.start_background()
            status, _, body = _request(server, "GET", "/v1/debug/fleet")
            assert status == 400
            assert json.loads(body)["error"]["code"] == "bad_request"
            status, _, body = _request(
                server, "GET", "/v1/debug/fleet?peers=127.0.0.1:1"
            )
            assert status == 502
            assert (
                json.loads(body)["error"]["code"] == "fleet_unreachable"
            )

    def test_debug_cli_requires_url_or_fleet(self, capsys):
        rc = tool_main(["debug"])
        assert rc == 1
        assert "daemon URL" in capsys.readouterr().err


# -- routed trace stitching over a live mesh -----------------------------------


class TestRoutedTraceMerge:
    def test_router_hop_spans_stitch_into_one_timeline(self, corpus, tmp_path):
        """The PR 19 acceptance pin: a client traceparent through the mesh
        ROUTER rides every router->replica hop as a fresh child span (the
        wire proxy records the received headers), lands in both the
        router's and the replica's flight-recorder docs, and trace-merge
        stitches the multi-process timeline on the shared trace-id."""
        from parquet_tpu.serve.mesh import MeshConfig, MeshRouter
        from parquet_tpu.testing.flaky_replica import FlakyReplica

        client_tid = "beef" * 8
        client_tp = "00-" + client_tid + "-" + "ab" * 8 + "-01"
        backend = ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ).start_background()
        proxy = FlakyReplica(backend.url, seed=0).start()  # a clean wire tap
        other = ScanServer(
            ServeConfig(port=0, root=str(corpus))
        ).start_background()
        router = MeshRouter(
            MeshConfig(
                port=0,
                replicas=(proxy.url, other.url),
                trace_sample_rate=1.0,  # keep every span tree
            )
        ).start_background()
        try:
            status, headers, body = _request(
                router,
                "POST",
                "/v1/scan",
                {"paths": "a.parquet"},
                headers={"traceparent": client_tp},
            )
            assert status == 200, body
            echoed = propagate.parse_traceparent(headers["traceparent"])
            assert echoed.trace_id == client_tid
            rid_router = headers["X-Request-Id"]
            # every hop the wire tap saw is OUR trace with a FRESH span
            assert proxy.traceparents, "no hop reached the tapped replica"
            spans = set()
            for raw in proxy.traceparents:
                got = propagate.parse_traceparent(raw)
                assert got is not None, raw
                assert got.trace_id == client_tid
                assert got.span_id != "ab" * 8
                spans.add(got.span_id)
            assert len(spans) == len(proxy.traceparents)
            # the shared in-process recorder holds BOTH sides' request
            # docs under the one trace-id; pick one per side and merge
            status, _, body = _request(router, "GET", "/v1/debug/requests")
            assert status == 200
            listed = json.loads(body)["requests"]
            rids = [r["id"] for r in listed if r.get("trace_id") == client_tid]
            assert rid_router in rids
            rid_replica = next(r for r in rids if r != rid_router)
            docs = []
            for rid in (rid_router, rid_replica):
                status, _, body = _request(
                    router, "GET", f"/v1/debug/requests/{rid}/trace"
                )
                assert status == 200, body
                doc = json.loads(body)
                assert (
                    doc["otherData"]["propagation"]["trace_id"] == client_tid
                )
                docs.append(doc)
            pa_, pb = tmp_path / "router.json", tmp_path / "replica.json"
            po = tmp_path / "merged.json"
            pa_.write_text(json.dumps(docs[0]))
            pb.write_text(json.dumps(docs[1]))
            rc = tool_main(["trace-merge", str(pa_), str(pb), "-o", str(po)])
            assert rc == 0
            merged = json.loads(po.read_text())
            assert (
                merged["otherData"]["propagation"]["trace_id"] == client_tid
            )
            assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}
        finally:
            router.close()
            proxy.close()
            backend.close()
            other.close()


# -- lane audit ----------------------------------------------------------------


class TestLaneCoverage:
    def test_every_pool_prefix_attributes_to_a_named_lane(self):
        """Grep the package for every pqt-* thread/pool name and pin that
        each attributes to a named profiler lane — a new pool added
        without a POOL_LANES entry fails here, not silently as "other"."""
        pkg = Path(__file__).resolve().parent.parent / "parquet_tpu"
        pat = re.compile(
            r"(?:thread_)?name(?:_prefix)?=f?\"(pqt-[a-z-]+)"
        )
        prefixes = set()
        for path in pkg.rglob("*.py"):
            prefixes.update(pat.findall(path.read_text()))
        assert len(prefixes) >= 10, prefixes  # the audit found the fleet
        for prefix in sorted(prefixes):
            # worker threads are named e.g. "pqt-io_3" / "pqt-serve-http"
            assert lane_of(f"{prefix}_0") != "other", prefix
            assert lane_of(prefix) != "other", prefix

    def test_lane_of_basics(self):
        assert lane_of("MainThread") == "main"
        assert lane_of("Thread-7") == "other"
        # specific lanes win over their prefixes
        assert lane_of("pqt-serve-http") == "pqt-serve-http"
        assert lane_of("pqt-serve_2") == "pqt-serve"


# -- the propagation scope rides pool hops -------------------------------------


class TestScopeAcrossPools:
    def test_instrumented_submit_carries_the_scope(self):
        from parquet_tpu.io.planner import io_pool
        from parquet_tpu.obs.pool import instrumented_submit

        ctx = propagate.mint()
        seen = []

        def probe():
            seen.append(propagate.outbound_traceparent("get"))

        with propagate.propagation_scope(ctx):
            instrumented_submit(io_pool(), probe, pool="pqt-io").result(
                timeout=WATCHDOG_S
            )
        assert seen and seen[0] is not None
        assert propagate.parse_traceparent(seen[0]).trace_id == ctx.trace_id

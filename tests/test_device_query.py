"""Device-resident query execution: differential parity vs the host engines.

The read-side twin of tests/test_device_encode.py's write matrix, closing
the HBM loop end to end:

  * core/filter_device.device_dnf_mask (through
    FileReader.read_row_group_device(filters=) and the
    iter_device_batches(filter_rows=True) compaction) must produce masks
    and batches BYTE-IDENTICAL to the host vec engine across the same
    type zoo test_filter_vec pins — ints, unsigned bit-pattern views,
    floats with NaN, decimals, strings/binary, bools, nulls everywhere,
    LIST `contains` — with every decline typed and counted into the host
    fallback, never divergent output;
  * serve/query_device.device_unit_partial (through
    ServeConfig(device=True) -> execute_query) must render query bodies
    identical to run_local_query's pyarrow-pinned host path, including
    the shapes OUTSIDE the device envelope (float sums, group_by,
    decimal domains) falling back typed-and-counted per unit;
  * FileWriter.write_device_column must produce files byte-identical to
    write_column across encodings x codecs x data-page versions.

Everything runs on CPU jax (conftest forces the platform); identity — not
speed — is the contract this suite pins.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

# x64 flips on at device_ops import: pull it in before ANY jnp array is
# built, or int64 test data silently truncates to int32
import parquet_tpu.kernels.device_ops  # noqa: E402,F401

from parquet_tpu.core.filter import normalize_dnf
from parquet_tpu.core.filter_vec import VecFilterError, dnf_mask
from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter
from parquet_tpu.sink import MemorySink
from parquet_tpu.schema.dsl import parse_schema
from parquet_tpu.utils import metrics
from tests.test_filter_vec import ZOO_FILTERS, zoo  # noqa: F401

jnp = jax.numpy


# -- resident masks vs the host vec engine -------------------------------------


class TestDeviceMaskParity:
    @pytest.mark.parametrize(
        "filt", ZOO_FILTERS, ids=[str(f) for f in ZOO_FILTERS]
    )
    def test_mask_parity_type_zoo(self, zoo, filt):
        """Per row group: the device mask (engine ladder included) equals
        the host vec mask bit for bit; where even the host vec engine
        declines, the device path must raise the SAME typed error."""
        with FileReader(zoo) as r:
            nd = normalize_dnf(r.schema, filt)
            for i in range(r.num_row_groups):
                n = int(r.row_group(i).num_rows or 0)
                chunks = r._read_row_group(i, None, pack=False)
                try:
                    host = dnf_mask(chunks, nd, n)
                except VecFilterError:
                    with pytest.raises(VecFilterError):
                        r.read_row_group_device(i, filters=filt)
                    return
                _cols, mask = r.read_row_group_device(i, filters=filt)
                np.testing.assert_array_equal(np.asarray(mask), host)

    def test_device_engine_engages_and_counts(self, zoo):
        snap = metrics.snapshot()
        with FileReader(zoo) as r:
            _cols, mask = r.read_row_group_device(0, filters=[("i32", ">", 100)])
            assert int(jnp.sum(mask)) > 0
        d = metrics.delta(snap)
        assert d.get('events_total{event="device_filter_engaged"}', 0) > 0
        assert not d.get('events_total{event="device_filter_declined"}', 0)

    def test_plain_bytearray_declines_to_host_identically(self, tmp_path):
        """PLAIN (non-dictionary) byte arrays have no resident ordering:
        the device engine declines, counted, and the host vec mask is
        uploaded instead — same bits either way."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        vals = [f"row{i:04d}" for i in range(500)]
        p = str(tmp_path / "plainba.parquet")
        pq.write_table(
            pa.table({"s": pa.array(vals)}), p, use_dictionary=False
        )
        filt = [("s", ">=", "row0250")]
        snap = metrics.snapshot()
        with FileReader(p) as r:
            nd = normalize_dnf(r.schema, filt)
            chunks = r._read_row_group(0, None, pack=False)
            host = dnf_mask(chunks, nd, 500)
            _cols, mask = r.read_row_group_device(0, filters=filt)
        np.testing.assert_array_equal(np.asarray(mask), host)
        d = metrics.delta(snap)
        assert d.get('events_total{event="device_filter_declined"}', 0) > 0

    def test_filter_columns_delivered_beyond_projection(self, zoo):
        """read_row_group_device(filters=) extends the read set to the
        filter leaves and does NOT compact: the caller applies the mask
        (mask_take_device) and drops filter-only columns itself."""
        with FileReader(zoo) as r:
            cols, mask = r.read_row_group_device(
                0, ["i64"], filters=[("i32", "<", 100)]
            )
            assert ("i64",) in cols and ("i32",) in cols
            n = int(r.row_group(0).num_rows)
            assert mask.shape == (n,)
            assert cols[("i64",)].num_values == n  # not compacted


# -- filter_rows=True batch compaction vs host rows ----------------------------


def _numeric_corpus(tmp_path, groups=4, rows=1500):
    schema = parse_schema(
        """
        message m {
          required int64 id;
          required int32 tag (UINT_32);
          required double v;
          optional int64 maybe;
        }
        """
    )
    rng = np.random.default_rng(31)
    p = str(tmp_path / "corpus.parquet")
    with FileWriter(p, schema, codec="snappy", row_group_size=1 << 30) as w:
        for g in range(groups):
            base = g * rows
            w.write_column("id", np.arange(base, base + rows, dtype=np.int64))
            w.write_column(
                "tag",
                rng.integers(0, 1 << 32, rows, dtype=np.uint64)
                .astype(np.uint32)
                .view(np.int32),
            )
            v = rng.standard_normal(rows)
            v[::97] = np.nan
            w.write_column("v", v)
            dl = (rng.random(rows) < 0.85).astype(np.uint16)
            w.write_column(
                "maybe",
                np.flatnonzero(dl).astype(np.int64),
                def_levels=dl,
            )
            w.flush_row_group()
    return p


BATCH_FILTERS = [
    [("id", ">=", 1000), ("id", "<", 5000)],
    [("tag", ">=", 1 << 31)],
    [("v", ">", 0.5)],  # NaNs fail
    [("maybe", "not_null"), ("v", "<", 0.0)],
    [("maybe", "is_null")],
    [[("id", "<", 700)], [("tag", "<", 1 << 20)]],  # OR of conjunctions
    [("id", "in", [3, 4000, 5999, 123456])],
]


class TestFilterRowsBatches:
    @pytest.mark.parametrize("filt", BATCH_FILTERS, ids=str)
    def test_batches_match_host_filtered_rows(self, tmp_path, filt):
        p = _numeric_corpus(tmp_path)
        with FileReader(p) as r:
            got_id, got_v = [], []
            for b in r.iter_device_batches(
                512,
                columns=["id", "v"],
                drop_remainder=False,
                filters=filt,
                filter_rows=True,
            ):
                got_id.append(np.asarray(b[("id",)]))
                got_v.append(np.asarray(b[("v",)]))
            rows = list(r.iter_rows(filters=filt))
        got_id = np.concatenate(got_id) if got_id else np.empty(0, np.int64)
        got_v = np.concatenate(got_v) if got_v else np.empty(0)
        np.testing.assert_array_equal(
            got_id, np.array([x["id"] for x in rows], dtype=np.int64)
        )
        # floats compare as bit patterns: NaN payloads must survive
        np.testing.assert_array_equal(
            got_v.view(np.uint64),
            np.array([x["v"] for x in rows]).view(np.uint64),
        )

    def test_filter_rows_requires_filters(self, tmp_path):
        p = _numeric_corpus(tmp_path, groups=1, rows=64)
        with FileReader(p) as r:
            with pytest.raises(ValueError, match="filter_rows"):
                next(r.iter_device_batches(8, filter_rows=True))

    def test_default_stays_group_granularity(self, tmp_path):
        """filter_rows defaults OFF: filters= alone prunes row GROUPS and
        surviving groups stream whole (pinned separately in
        test_tpu_backend.test_device_batches_filter_pushdown)."""
        p = _numeric_corpus(tmp_path, groups=2, rows=1000)
        with FileReader(p) as r:
            n = sum(
                int(b[("id",)].shape[0])
                for b in r.iter_device_batches(
                    250, columns=["id"], filters=[("id", "<", 10)]
                )
            )
        assert n == 1000  # whole first group, rows NOT individually masked


# -- device partial aggregation through the serve executor ---------------------


def _agg_corpus(tmp_path):
    schema = parse_schema(
        """
        message m {
          required int64 id;
          required int32 u (UINT_32);
          optional int64 maybe;
          required double score;
          required int32 dec (DECIMAL(9, 2));
          required binary name (UTF8);
        }
        """
    )
    rng = np.random.default_rng(41)
    p = str(tmp_path / "agg.parquet")
    rows, groups = 1200, 3
    with FileWriter(p, schema, codec="snappy", row_group_size=1 << 30) as w:
        for g in range(groups):
            n = rows
            w.write_column(
                "id", rng.integers(-(10**12), 10**12, n).astype(np.int64)
            )
            w.write_column(
                "u",
                rng.integers(0, 1 << 32, n, dtype=np.uint64)
                .astype(np.uint32)
                .view(np.int32),
            )
            dl = (rng.random(n) < 0.8).astype(np.uint16)
            w.write_column(
                "maybe",
                rng.integers(0, 1000, int(dl.sum())).astype(np.int64),
                def_levels=dl,
            )
            w.write_column("score", rng.standard_normal(n))
            w.write_column("dec", rng.integers(-5000, 5000, n).astype(np.int32))
            w.write_column(
                "name", [["x", "y", "zz"][i % 3] for i in range(n)]
            )
            w.flush_row_group()
    return p


AGG_BODIES = [
    # inside the device envelope: global integer count/sum/min/max
    {"aggregates": ["count"]},
    {
        "aggregates": [
            "count",
            {"op": "sum", "column": "id"},
            {"op": "min", "column": "id"},
            {"op": "max", "column": "id"},
        ]
    },
    {"aggregates": [{"op": "sum", "column": "u"}, {"op": "max", "column": "u"}]},
    {"aggregates": [{"op": "count", "column": "maybe"},
                    {"op": "sum", "column": "maybe"}]},
    {
        "aggregates": ["count", {"op": "sum", "column": "id"}],
        "filters": [["id", ">", 0]],
    },
    {
        "aggregates": [{"op": "min", "column": "maybe"}],
        "filters": [["name", "==", "zz"]],
    },
    {
        "aggregates": ["count", {"op": "sum", "column": "id"}],
        "filters": [["maybe", "not_in", [1, 2]]],  # arrow null convention
    },
    {
        "aggregates": [{"op": "max", "column": "id"}],
        "filters": [["id", "<", -(10**13)]],  # zero matches -> null
    },
    # OUTSIDE the envelope: typed per-unit fallback to the host path
    {"aggregates": [{"op": "sum", "column": "score"}]},  # float domain
    {"aggregates": [{"op": "sum", "column": "dec"}]},  # decimal domain
    {"aggregates": ["count"], "group_by": ["name"]},  # hash groupby
]


@pytest.fixture(scope="module")
def agg_setup(tmp_path_factory):
    from parquet_tpu.serve.server import ScanService, ServeConfig

    tmp = tmp_path_factory.mktemp("device_agg")
    path = _agg_corpus(tmp)
    svc = ScanService(ServeConfig(root=str(tmp), device=True))
    return path, svc


class TestDeviceAggregates:
    def _body(self, path, body):
        from parquet_tpu.serve.protocol import parse_query_request

        return parse_query_request(
            json.dumps({"paths": [path], **body}).encode()
        )

    @pytest.mark.parametrize("body", AGG_BODIES, ids=lambda b: json.dumps(b))
    def test_device_query_matches_host(self, agg_setup, body):
        from parquet_tpu.serve.aggregate import (
            render_query_body,
            run_local_query,
        )

        path, svc = agg_setup
        q = self._body(path, body)
        host = render_query_body(run_local_query(q.paths, q))
        ticket, got = svc.query(q, "test")
        ticket.release()
        assert render_query_body(got) == host

    def test_units_counted_by_engine(self, agg_setup):
        path, svc = agg_setup
        snap = metrics.snapshot()
        for body in (
            {"aggregates": [{"op": "sum", "column": "id"}]},  # device
            {"aggregates": ["count"], "group_by": ["name"]},  # fallback
        ):
            ticket, _ = svc.query(self._body(path, body), "test")
            ticket.release()
        d = metrics.delta(snap)
        assert d.get('query_device_units_total{engine="device"}', 0) > 0
        assert d.get('query_device_units_total{engine="host_fallback"}', 0) > 0

    def test_host_config_never_routes_device(self, agg_setup, tmp_path):
        from parquet_tpu.serve.server import ScanService, ServeConfig

        import os

        path, _svc = agg_setup
        host_svc = ScanService(ServeConfig(root=os.path.dirname(path)))
        snap = metrics.snapshot()
        ticket, _ = host_svc.query(
            self._body(path, {"aggregates": ["count"]}), "test"
        )
        ticket.release()
        d = metrics.delta(snap)
        assert not d.get('query_device_units_total{engine="device"}', 0)


# -- the device write path: byte identity across the encode matrix -------------


def _write_both(codec, dpv, with_crc=False, rows=900):
    """(host_bytes, device_bytes) for a 4-column file covering the PLAIN,
    RLE_DICTIONARY, DELTA_BINARY_PACKED and byte-array device routes."""
    schema = parse_schema(
        """
        message w {
          required int64 hi;
          required int64 lo;
          required int64 seq;
          required binary s (UTF8);
        }
        """
    )
    rng = np.random.default_rng(47)
    hi = rng.integers(-(2**60), 2**60, rows).astype(np.int64)  # PLAIN
    lo = rng.integers(0, 50, rows).astype(np.int64)  # dictionary
    seq = np.cumsum(rng.integers(0, 7, rows)).astype(np.int64)  # DELTA
    strs = [f"s{i % 37}" for i in range(rows)]
    data = np.frombuffer("".join(strs).encode(), dtype=np.uint8)
    offsets = np.zeros(rows + 1, dtype=np.int64)
    np.cumsum([len(s) for s in strs], out=offsets[1:])

    def write(device):
        sink = MemorySink()
        w = FileWriter(
            sink,
            schema,
            codec=codec,
            data_page_version=dpv,
            with_crc=with_crc,
            column_encodings={"seq": "DELTA_BINARY_PACKED"},
        )
        for _ in range(2):
            if device:
                w.write_device_column("hi", jnp.asarray(hi))
                w.write_device_column("lo", jnp.asarray(lo))
                w.write_device_column("seq", jnp.asarray(seq))
                w.write_device_column(
                    "s", (jnp.asarray(data), jnp.asarray(offsets))
                )
            else:
                w.write_column("hi", hi)
                w.write_column("lo", lo)
                w.write_column("seq", seq)
                w.write_column("s", strs)
            w.flush_row_group()
        w.close()
        return sink.getvalue()

    return write(False), write(True)


class TestDeviceWriteMatrix:
    @pytest.mark.parametrize(
        "codec,dpv", [("snappy", 2), ("uncompressed", 1)], ids=str
    )
    def test_byte_identical_fast(self, codec, dpv):
        snap = metrics.snapshot()
        host, dev = _write_both(codec, dpv)
        assert host == dev
        d = metrics.delta(snap)
        assert d.get('events_total{event="device_write_engaged"}', 0) > 0

    @pytest.mark.slow
    @pytest.mark.parametrize("with_crc", [False, True], ids=["nocrc", "crc"])
    @pytest.mark.parametrize("dpv", [1, 2])
    @pytest.mark.parametrize("codec", ["uncompressed", "snappy", "gzip"])
    def test_byte_identical_full_matrix(self, codec, dpv, with_crc):
        host, dev = _write_both(codec, dpv, with_crc=with_crc)
        assert host == dev

    def test_byte_stream_split_falls_back_identically(self):
        schema = parse_schema("message w { required double x; }")
        rng = np.random.default_rng(3)
        x = rng.standard_normal(400)

        def write(device):
            sink = MemorySink()
            w = FileWriter(
                sink, schema, column_encodings={"x": "BYTE_STREAM_SPLIT"}
            )
            if device:
                w.write_device_column("x", jnp.asarray(x))
            else:
                w.write_column("x", x)
            w.close()
            return sink.getvalue()

        snap = metrics.snapshot()
        host, dev = write(False), write(True)
        assert host == dev
        d = metrics.delta(snap)
        assert d.get('events_total{event="device_write_declined"}', 0) > 0


# -- dataset filter_rows -------------------------------------------------------


class TestDatasetFilterRows:
    def test_rows_filtered_and_filter_columns_dropped(self, tmp_path):
        from parquet_tpu.data.dataset import ParquetDataset

        p = _numeric_corpus(tmp_path, groups=3, rows=1000)
        filt = [("id", ">=", 500), ("id", "<", 2500), ("tag", ">=", 1 << 31)]
        ds = ParquetDataset(
            p,
            batch_size=128,
            columns=["id", "v"],
            filters=filt,
            filter_rows=True,
            remainder="keep",
            prefetch=0,
        )
        got_id, got_v = [], []
        for b in ds:
            assert set(b) == {("id",), ("v",)}  # tag read but not delivered
            got_id.append(np.asarray(b[("id",)]))
            got_v.append(np.asarray(b[("v",)]))
        got_id = np.concatenate(got_id)
        got_v = np.concatenate(got_v)
        with FileReader(p) as r:
            rows = list(r.iter_rows(filters=filt))
        np.testing.assert_array_equal(
            got_id, np.array([x["id"] for x in rows], dtype=np.int64)
        )
        np.testing.assert_array_equal(
            got_v.view(np.uint64),
            np.array([x["v"] for x in rows]).view(np.uint64),
        )

    def test_filter_rows_requires_filters(self, tmp_path):
        from parquet_tpu.data.dataset import ParquetDataset

        with pytest.raises(ValueError, match="filter_rows"):
            ParquetDataset(
                str(tmp_path / "x.parquet"), batch_size=8, filter_rows=True
            )

    def test_resume_reproduces_filtered_tail(self, tmp_path):
        from parquet_tpu.data.dataset import ParquetDataset

        p = _numeric_corpus(tmp_path, groups=3, rows=1000)
        filt = [("id", "<", 2200)]

        def make():
            return ParquetDataset(
                p,
                batch_size=100,
                columns=["id"],
                filters=filt,
                filter_rows=True,
                remainder="keep",
                prefetch=0,
            )

        it = iter(make())
        for _ in range(4):
            next(it)
        state = it.state_dict()
        rest = [np.asarray(b[("id",)]) for b in it]
        it2 = iter(make())
        it2.load_state_dict(state)
        rest2 = [np.asarray(b[("id",)]) for b in it2]
        assert len(rest) == len(rest2)
        for a, b in zip(rest, rest2):
            np.testing.assert_array_equal(a, b)


# -- the extended slow sweep ---------------------------------------------------


@pytest.mark.slow
class TestExtendedSweep:
    def test_mask_parity_random_predicates(self, zoo):
        """Randomized DNF shapes over the zoo, device vs host per group —
        the long tail the enumerated list can't reach."""
        rng = np.random.default_rng(77)
        ops = ["==", "!=", "<", "<=", ">", ">="]
        cols = [
            ("i32", lambda: int(rng.integers(-10, 810))),
            ("i64", lambda: int(rng.integers(-500, 500))),
            ("u32", lambda: (1 << 31) + int(rng.integers(0, 800))),
            ("f", lambda: float(rng.standard_normal())),
            ("s", lambda: f"v{int(rng.integers(0, 25))}"),
        ]
        with FileReader(zoo) as r:
            for _ in range(60):
                conj = []
                for _ in range(int(rng.integers(1, 4))):
                    name, gen = cols[int(rng.integers(0, len(cols)))]
                    conj.append((name, ops[int(rng.integers(0, len(ops)))], gen()))
                filt = [conj]
                nd = normalize_dnf(r.schema, filt)
                for i in range(r.num_row_groups):
                    n = int(r.row_group(i).num_rows or 0)
                    chunks = r._read_row_group(i, None, pack=False)
                    try:
                        host = dnf_mask(chunks, nd, n)
                    except VecFilterError:
                        continue
                    _c, mask = r.read_row_group_device(i, filters=filt)
                    np.testing.assert_array_equal(
                        np.asarray(mask), host, err_msg=str(filt)
                    )

    def test_filtered_rows_match_iter_rows_full_zoo(self, zoo):
        """Every zoo filter the host vec engine accepts, compacted on
        device (numeric projection) vs the row oracle."""
        with FileReader(zoo) as r:
            for filt in ZOO_FILTERS:
                try:
                    rows = list(r.iter_rows(filters=filt))
                except Exception:
                    continue
                try:
                    got = [
                        np.asarray(b[("i32",)])
                        for b in r.iter_device_batches(
                            128,
                            columns=["i32"],
                            drop_remainder=False,
                            filters=filt,
                            filter_rows=True,
                        )
                    ]
                except VecFilterError:
                    continue
                flat = (
                    np.concatenate(got) if got else np.empty(0, np.int32)
                )
                np.testing.assert_array_equal(
                    flat,
                    np.array([x["i32"] for x in rows], dtype=np.int32),
                    err_msg=str(filt),
                )

"""parquet_tpu.data: the streaming dataset subsystem's contracts.

Pinned here:
  * plan determinism: glob order, unit layout, filter pruning, corrupt-file
    skipping at plan time;
  * sharding: every unit visited by exactly one shard per epoch, shuffled
    or not, for shard counts 1/2/4 (and the worker sub-split);
  * the batch stream equals the source rows, rebatched with carry across
    unit boundaries; remainder modes drop/keep/pad;
  * mid-epoch checkpoint/resume reproduces the remaining batch stream
    BYTE-IDENTICALLY across shuffle seeds and shard counts — including a
    cursor inside a unit;
  * on_error="skip": a corrupt page quarantines only its row group, an
    unreadable footer drops only its file, and every clean row still
    arrives exactly once;
  * the prefetch pipeline survives concurrency (two iterators on two
    threads, bounded queue) under a watchdog — a deadlock fails fast
    instead of hanging CI;
  * device delivery: batches land as jax arrays (and sharded over a mesh)
    with the same values as host delivery.
"""

from __future__ import annotations

import glob
import shutil
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.data import ParquetDataset, build_plan, expand_paths
from parquet_tpu.meta.file_meta import ParquetFileError
from parquet_tpu.utils import metrics

WATCHDOG_SECONDS = 60.0

N_FILES = 5
ROWS = [700, 800, 900, 1000, 1100]  # per file; row_group_size=300 -> 3-4 units
ROW_GROUP = 300


def _write_shards(d, rows=ROWS, seed=0, nulls=False):
    rng = np.random.default_rng(seed)
    paths = []
    for i, n in enumerate(rows):
        mask = (rng.random(n) < 0.2) if nulls else None
        t = pa.table(
            {
                "x": pa.array(
                    rng.standard_normal(n).astype(np.float32), mask=mask
                ),
                "y": pa.array(rng.integers(0, 1 << 40, n).astype(np.int64)),
            }
        )
        p = str(d / f"shard-{i:03d}.parquet")
        pq.write_table(t, p, row_group_size=ROW_GROUP)
        paths.append(p)
    return paths


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("dataset_shards")
    _write_shards(d)
    return d


@pytest.fixture(scope="module")
def pattern(shard_dir):
    return str(shard_dir / "shard-*.parquet")


def _source_rows(pattern):
    """Concatenated source columns in file-major order (the no-shuffle
    stream's reference)."""
    xs, ys = [], []
    for p in sorted(glob.glob(pattern)):
        t = pq.read_table(p)
        xs.append(t.column("x").to_numpy())
        ys.append(t.column("y").to_numpy())
    return np.concatenate(xs), np.concatenate(ys)


def _drain(it):
    return [{k: np.asarray(v) for k, v in b.items()} for b in it]


def _batches_equal(a, b):
    assert len(a) == len(b), (len(a), len(b))
    for ba, bb in zip(a, b):
        assert ba.keys() == bb.keys()
        for k in ba:
            assert np.array_equal(ba[k], bb[k]), k


def with_watchdog(fn, timeout: float = WATCHDOG_SECONDS):
    """Run fn on a daemon thread; a hang FAILS loudly instead of stalling
    the suite (same harness shape as test_faults)."""
    result: dict = {}

    def target():
        try:
            result["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on main thread
            result["error"] = e

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        pytest.fail(f"watchdog: dataset still running after {timeout}s (hang)")
    if "error" in result:
        raise result["error"]
    return result.get("value")


class TestPlan:
    def test_units_and_rows(self, pattern):
        plan = build_plan(pattern)
        assert plan.num_units == sum(-(-n // ROW_GROUP) for n in ROWS)
        assert plan.total_rows == sum(ROWS)
        # file-major, group-minor, lexicographic file order
        assert [u.row_group for u in plan.units[:3]] == [0, 1, 2]
        assert plan.units[0].path <= plan.units[-1].path

    def test_expand_paths_sorted_and_errors(self, pattern, shard_dir):
        files = expand_paths(pattern)
        assert files == sorted(files) and len(files) == N_FILES
        assert expand_paths(files[0]) == [files[0]]
        with pytest.raises(FileNotFoundError):
            expand_paths(str(shard_dir / "nope-*.parquet"))
        with pytest.raises(ValueError):
            expand_paths([])

    def test_filters_prune_units(self, pattern):
        # y >= 0 admits everything; an impossible predicate prunes all units
        assert build_plan(pattern, filters=[("y", ">=", 0)]).num_units > 0
        assert build_plan(pattern, filters=[("y", "<", -1)]).num_units == 0

    def test_epoch_order_is_seed_epoch_function(self, pattern):
        plan = build_plan(pattern)
        a = plan.epoch_order(3, seed=5, shuffle=True)
        b = plan.epoch_order(3, seed=5, shuffle=True)
        c = plan.epoch_order(4, seed=5, shuffle=True)
        d = plan.epoch_order(3, seed=6, shuffle=True)
        assert a == b
        assert a != c and a != d  # different epoch/seed reshuffle
        assert sorted(a) == list(range(plan.num_units))

    @pytest.mark.parametrize("shuffle", [False, True])
    @pytest.mark.parametrize("count", [1, 2, 4])
    def test_shards_partition_exactly_once(self, pattern, shuffle, count):
        plan = build_plan(pattern)
        seen = []
        for i in range(count):
            seen.extend(
                plan.epoch_order(
                    1, seed=2, shuffle=shuffle, shard_index=i, shard_count=count
                )
            )
        assert sorted(seen) == list(range(plan.num_units))

    def test_worker_subsplit_partitions(self, pattern):
        plan = build_plan(pattern)
        units = []
        for si in range(2):
            for wi in range(2):
                ds = ParquetDataset(
                    pattern, batch_size=64, shard=(si, 2), worker=(wi, 2),
                    shuffle=True, seed=1,
                )
                units.extend(ds.epoch_order(0))
        assert sorted(units) == list(range(plan.num_units))


class TestStream:
    @pytest.mark.parametrize("prefetch", [0, 2])
    def test_matches_source_order(self, pattern, prefetch):
        xs, ys = _source_rows(pattern)
        ds = ParquetDataset(
            pattern, batch_size=256, prefetch=prefetch, remainder="keep"
        )
        got = _drain(iter(ds))
        gx = np.concatenate([b[("x",)] for b in got])
        gy = np.concatenate([b[("y",)] for b in got])
        assert np.array_equal(gx, xs) and np.array_equal(gy, ys)
        assert all(b[("x",)].shape[0] == 256 for b in got[:-1])

    def test_remainder_modes(self, pattern):
        total = sum(ROWS)
        b = 512
        full = total // b
        drop = _drain(iter(ParquetDataset(pattern, batch_size=b)))
        assert len(drop) == full and all(
            x[("x",)].shape[0] == b for x in drop
        )
        keep = _drain(
            iter(ParquetDataset(pattern, batch_size=b, remainder="keep"))
        )
        assert len(keep) == full + 1
        assert keep[-1][("x",)].shape[0] == total - full * b
        pad = _drain(
            iter(ParquetDataset(pattern, batch_size=b, remainder="pad"))
        )
        assert len(pad) == full + 1
        assert pad[-1][("x",)].shape[0] == b
        tail = total - full * b
        assert np.all(pad[-1][("x",)][tail:] == 0)
        assert np.array_equal(pad[-1][("x",)][:tail], keep[-1][("x",)])

    def test_carry_crosses_unit_boundaries(self, pattern):
        # batch > unit size forces every batch to span units
        ds = ParquetDataset(pattern, batch_size=450, remainder="keep")
        xs, _ = _source_rows(pattern)
        got = np.concatenate([np.asarray(b[("x",)]) for b in ds])
        assert np.array_equal(got, xs)

    def test_multi_epoch_reshuffles(self, pattern):
        ds = ParquetDataset(
            pattern, batch_size=300, shuffle=True, seed=4, num_epochs=2,
            remainder="keep",
        )
        batches = _drain(iter(ds))
        half = len(batches) // 2
        e0 = np.concatenate([b[("y",)] for b in batches[:half]])
        e1 = np.concatenate([b[("y",)] for b in batches[half:]])
        assert not np.array_equal(e0, e1)  # different epoch order
        assert np.array_equal(np.sort(e0), np.sort(e1))  # same multiset

    def test_nulls_raise_by_default_and_zero_fill(self, tmp_path):
        _write_shards(tmp_path, rows=[600], nulls=True)
        p = str(tmp_path / "shard-000.parquet")
        with pytest.raises(ParquetFileError, match="nulls"):
            _drain(iter(ParquetDataset(p, batch_size=100)))
        ds = ParquetDataset(p, batch_size=100, nullable="zero")
        got = np.concatenate([np.asarray(b[("x",)]) for b in ds])
        want = pq.read_table(p).column("x").to_numpy(zero_copy_only=False)
        want = np.where(np.isnan(want), 0, want).astype(np.float32)
        assert np.array_equal(got, want[: len(got)])

    def test_schema_mismatch_across_files(self, tmp_path):
        _write_shards(tmp_path, rows=[400])
        t = pa.table({"x": pa.array(np.arange(400, dtype=np.int32)),
                      "y": pa.array(np.arange(400, dtype=np.int64))})
        pq.write_table(t, tmp_path / "shard-zzz.parquet", row_group_size=200)
        ds = ParquetDataset(
            str(tmp_path / "shard-*.parquet"), batch_size=128
        )
        with pytest.raises(ParquetFileError, match="schema mismatch"):
            _drain(iter(ds))

    def test_bad_projection_raises_even_under_skip(self, pattern):
        """A misspelled columns= or filter column is a CONFIG error, not
        corruption: on_error='skip' must not quarantine every unit into a
        silently empty dataset."""
        ds = ParquetDataset(
            pattern, batch_size=128, columns=["nope"], on_error="skip"
        )
        with pytest.raises(ParquetFileError, match="not in schema"):
            ds.plan  # noqa: B018
        with pytest.raises(ValueError):
            build_plan(pattern, filters=[("nope", ">=", 0)], on_error="skip")

    def test_closed_dataset_refuses_iteration(self, pattern):
        ds = ParquetDataset(pattern, batch_size=128, prefetch=2)
        it = iter(ds)
        next(it)
        it.close()  # releases its in-flight prefetch accounting
        ds.close()
        ds.close()  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            iter(ds)

    def test_config_validation(self, pattern):
        with pytest.raises(ValueError):
            ParquetDataset(pattern, batch_size=0)
        with pytest.raises(ValueError):
            ParquetDataset(pattern, batch_size=8, remainder="nope")
        with pytest.raises(ValueError):
            ParquetDataset(pattern, batch_size=8, on_error="null")
        with pytest.raises(ValueError):
            ParquetDataset(pattern, batch_size=8, shard=(2, 2))
        with pytest.raises(ValueError):
            ParquetDataset(pattern, batch_size=8, prefetch=-1)
        with pytest.raises(ValueError, match='only shard= accepts "jax"'):
            ParquetDataset(pattern, batch_size=8, worker="jax")

    def test_sync_path_records_wait(self, pattern):
        """prefetch=0 blocks on every decode — wait_share must say so, not
        read 0% at the one depth where starvation is total."""
        s0 = metrics.snapshot()
        _drain(iter(ParquetDataset(pattern, batch_size=512, prefetch=0)))
        d = metrics.delta(s0)
        assert d.get("dataset_wait_seconds_count", 0) > 0
        assert d.get("dataset_wait_seconds_sum", 0) > 0


class TestCheckpoint:
    @pytest.mark.parametrize("count", [1, 2, 4])
    @pytest.mark.parametrize("seed", [0, 7])
    def test_resume_byte_identical(self, pattern, count, seed):
        for index in range(count):
            ds = ParquetDataset(
                pattern, batch_size=192, shuffle=True, seed=seed,
                shard=(index, count), num_epochs=2, remainder="keep",
            )
            it = iter(ds)
            consumed = 0
            head = []
            # cut mid-epoch, mid-unit: 192 does not divide the 300-row units
            for b in it:
                head.append(b)
                consumed += 1
                if consumed == 3:
                    break
            state = it.state_dict()
            rest = _drain(it)
            it2 = ParquetDataset(
                pattern, batch_size=192, shuffle=True, seed=seed,
                shard=(index, count), num_epochs=2, remainder="keep",
                prefetch=0,  # prefetch config is free to differ on resume
            ).iterator(state=state)
            _batches_equal(rest, _drain(it2))

    def test_state_covers_delivered_batches_only(self, pattern):
        ds = ParquetDataset(pattern, batch_size=256, num_epochs=1)
        it = iter(ds)
        s0 = it.state_dict()
        assert (s0["epoch"], s0["unit_pos"], s0["row_offset"]) == (0, 0, 0)
        first = next(it)
        s1 = it.state_dict()
        it2 = ds.iterator(state=s1)
        rest1 = _drain(it)
        rest2 = _drain(it2)
        _batches_equal(rest1, rest2)
        # and resuming from s0 replays the FIRST batch too
        replay = next(ds.iterator(state=s0))
        assert np.array_equal(
            np.asarray(replay[("x",)]), np.asarray(first[("x",)])
        )

    def test_exhausted_state_resumes_empty(self, pattern):
        ds = ParquetDataset(pattern, batch_size=512, num_epochs=1)
        it = iter(ds)
        _drain(it)
        state = it.state_dict()
        assert state["exhausted"]
        assert _drain(ds.iterator(state=state)) == []

    def test_mismatched_config_rejected(self, pattern):
        ds = ParquetDataset(pattern, batch_size=128)
        state = iter(ds).state_dict()
        for kw in (
            {"batch_size": 64},
            {"batch_size": 128, "seed": 9, "shuffle": True},
            {"batch_size": 128, "shard": (0, 2)},
        ):
            other = ParquetDataset(pattern, **kw)
            with pytest.raises(ValueError, match="mismatch"):
                other.iterator(state=state)

    def test_changed_file_set_rejected_moved_dir_accepted(self, tmp_path):
        """Same aggregate counts, different unit list: the fingerprint
        digest must reject the cursor (renamed shards are the classic
        re-materialization trap); moving the intact directory must NOT
        (basenames, not full paths, are pinned)."""
        _write_shards(tmp_path, rows=[600, 600])
        pat = str(tmp_path / "shard-*.parquet")
        ds = ParquetDataset(pat, batch_size=100, remainder="keep")
        it = iter(ds)
        for _ in range(3):
            next(it)
        state = it.state_dict()
        rest = _drain(it)
        # whole-directory move with names intact: resume byte-identical
        moved = tmp_path / "moved"
        moved.mkdir()
        for p in sorted(tmp_path.glob("shard-*.parquet")):
            p.rename(moved / p.name)
        at_new_home = ParquetDataset(
            str(moved / "shard-*.parquet"), batch_size=100, remainder="keep"
        )
        _batches_equal(rest, _drain(at_new_home.iterator(state=state)))
        # renaming one shard reorders/renames the unit list: rejected even
        # though files/units/rows all still match
        (moved / "shard-000.parquet").rename(moved / "shard-009.parquet")
        renamed = ParquetDataset(
            str(moved / "shard-*.parquet"), batch_size=100, remainder="keep"
        )
        with pytest.raises(ValueError, match="plan mismatch"):
            renamed.iterator(state=state)

    def test_started_iterator_rejects_load(self, pattern):
        ds = ParquetDataset(pattern, batch_size=128)
        it = iter(ds)
        state = it.state_dict()
        next(it)
        with pytest.raises(RuntimeError):
            it.load_state_dict(state)


class TestFaults:
    def test_skip_delivers_clean_rows_exactly_once(self, tmp_path):
        paths = _write_shards(tmp_path)
        # corrupt ONE row group of one extra file: stomp its first data page
        bad_page = tmp_path / "zz-badpage.parquet"
        shutil.copy(paths[0], bad_page)
        meta = FileReader.open_metadata(bad_page)
        cc = meta.row_groups[0].columns[0].meta_data
        with open(bad_page, "r+b") as f:
            f.seek(cc.data_page_offset + 16)
            f.write(b"\xff" * 64)
        # and one file whose footer is garbage
        bad_footer = tmp_path / "zz-badfooter.parquet"
        bad_footer.write_bytes(b"PAR1this is not a parquet footerPAR1")

        everything = str(tmp_path / "*.parquet")
        with pytest.raises(ParquetFileError):
            ParquetDataset(everything, batch_size=100).plan  # noqa: B018

        s0 = metrics.snapshot()
        ds = ParquetDataset(
            everything, batch_size=100, on_error="skip", shuffle=True,
            seed=11, remainder="keep",
        )
        got = np.concatenate([np.asarray(b[("y",)]) for b in ds])
        d = metrics.delta(s0)
        assert d.get('events_total{event="dataset_files_skipped"}') == 1
        assert d.get('events_total{event="dataset_units_skipped"}') == 1
        assert [p for p, _ in ds.plan.skipped_files] == [str(bad_footer)]

        # clean shards' rows exactly once, plus bad_page's SURVIVING groups
        clean_y = [
            pq.read_table(p).column("y").to_numpy() for p in paths
        ]
        surviving = pq.read_table(paths[0]).column("y").to_numpy()[ROW_GROUP:]
        want = np.sort(np.concatenate(clean_y + [surviving]))
        assert np.array_equal(np.sort(got), want)

    def test_corpus_shard_degrades(self, tmp_path):
        """One shard from the committed corrupt corpus rides a clean glob:
        the dataset's skip accounting must agree exactly with FileReader's
        own quarantine of the same file (clean file's rows + the corrupt
        file's surviving rows, nothing twice)."""
        import os

        corpus = os.path.join(
            os.path.dirname(__file__), "data", "corrupt"
        )
        shutil.copy(os.path.join(corpus, "pristine.parquet"),
                    tmp_path / "a-clean.parquet")
        # page_header_garbage: footer intact (units planned), a page fails
        # at decode -> its row group quarantines; truncated_mid_page: footer
        # gone -> whole file skipped at plan time
        for name in ("page_header_garbage", "truncated_mid_page"):
            shutil.copy(os.path.join(corpus, f"{name}.parquet"),
                        tmp_path / f"b-{name}.parquet")
        # what the reader itself salvages from the damaged files
        surviving = []
        for name in ("page_header_garbage", "truncated_mid_page"):
            try:
                with FileReader(
                    str(tmp_path / f"b-{name}.parquet"), columns=["id"],
                    on_error="skip",
                ) as r:
                    surviving.extend(
                        np.asarray(c[("id",)].values)
                        for c in (
                            r._read_row_group(g, None, pack=False)
                            for g in range(r.num_row_groups)
                        )
                        if c
                    )
            except ParquetFileError:
                pass  # unreadable footer: the file contributes nothing
        ds = ParquetDataset(
            str(tmp_path / "*.parquet"), batch_size=97, columns=["id"],
            on_error="skip", nullable="zero", remainder="keep",
        )
        got = np.concatenate([np.asarray(b[("id",)]) for b in ds])
        clean = pq.read_table(
            tmp_path / "a-clean.parquet"
        ).column("id").to_numpy()
        want = np.sort(np.concatenate([clean] + surviving))
        assert np.array_equal(np.sort(got), want)

    def test_null_policy_zero_fills_corrupt_chunk(self, tmp_path):
        paths = _write_shards(tmp_path, rows=[600])
        want_y = pq.read_table(paths[0]).column("y").to_numpy()
        meta = FileReader.open_metadata(paths[0])
        cc = meta.row_groups[0].columns[0].meta_data  # column "x"
        with open(paths[0], "r+b") as f:
            f.seek(cc.data_page_offset + 16)
            f.write(b"\xff" * 64)
        ds = ParquetDataset(
            paths, batch_size=100, on_error="null", nullable="zero",
            remainder="keep",
        )
        got = _drain(iter(ds))
        # no rows lost: the corrupt x-chunk delivers as zeros, row-aligned
        # with the intact y column of the same group
        assert sum(b[("x",)].shape[0] for b in got) == 600
        x = np.concatenate([b[("x",)] for b in got])
        y = np.concatenate([b[("y",)] for b in got])
        assert np.all(x[:ROW_GROUP] == 0)
        assert np.array_equal(y, want_y)

    def test_raise_policy_propagates(self, tmp_path):
        paths = _write_shards(tmp_path, rows=[500])
        bad = tmp_path / "zz-bad.parquet"
        shutil.copy(paths[0], bad)
        meta = FileReader.open_metadata(bad)
        cc = meta.row_groups[0].columns[0].meta_data
        with open(bad, "r+b") as f:
            f.seek(cc.data_page_offset + 16)
            f.write(b"\xff" * 64)
        from parquet_tpu.core.reader import PARQUET_ERRORS

        ds = ParquetDataset(str(tmp_path / "*.parquet"), batch_size=100)
        with pytest.raises(PARQUET_ERRORS):
            _drain(iter(ds))


class TestPrefetch:
    def test_two_iterators_two_threads_watchdog(self, pattern):
        """Tier-1 loader stress: concurrent iterators over one dataset's
        bounded pool must neither deadlock nor cross their streams."""
        xs, _ = _source_rows(pattern)

        def run():
            ds = ParquetDataset(
                pattern, batch_size=128, prefetch=2, remainder="keep"
            )
            out = [None, None]
            errs = []

            def worker(slot):
                try:
                    out[slot] = np.concatenate(
                        [np.asarray(b[("x",)]) for b in ds]
                    )
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [
                threading.Thread(target=worker, args=(i,)) for i in range(2)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(WATCHDOG_SECONDS)
            assert not errs, errs
            return out

        out = with_watchdog(run)
        for got in out:
            assert got is not None and np.array_equal(got, xs)

    def test_close_mid_stream_cancels(self, pattern):
        ds = ParquetDataset(pattern, batch_size=100, prefetch=3)
        it = iter(ds)
        next(it)
        it.close()
        with pytest.raises(StopIteration):
            next(it)
        ds.close()  # idempotent, queued work cancelled
        ds.close()

    def test_wait_metrics_and_gauge(self, pattern):
        s0 = metrics.snapshot()
        ds = ParquetDataset(pattern, batch_size=512, prefetch=2)
        n = len(_drain(iter(ds)))
        d = metrics.delta(s0)
        assert d.get("dataset_batches_total") == n
        assert d.get("dataset_rows_total") == n * 512
        assert d.get("dataset_wait_seconds_count", 0) > 0
        # the gauge exists, settles to 0 after the drain, and is a gauge in
        # the exposition
        assert metrics.get("dataset_prefetch_depth") == 0
        assert (
            "# TYPE parquet_tpu_dataset_prefetch_depth gauge"
            in metrics.render_prometheus()
        )


class TestTraceSpans:
    def test_dataset_spans_recorded(self, pattern):
        from parquet_tpu.utils.trace import decode_trace

        with decode_trace() as t:
            ds = ParquetDataset(pattern, batch_size=512, prefetch=2)
            _drain(iter(ds))
        names = {e[0] for e in t._events}
        assert "dataset.unit" in names
        assert "dataset.wait" in t.stages


class TestDevice:
    def test_device_batches_match_host(self, pattern):
        import jax

        host = _drain(
            iter(ParquetDataset(pattern, batch_size=256, num_epochs=1))
        )
        dev_ds = ParquetDataset(
            pattern, batch_size=256, num_epochs=1, device=jax.devices()[0]
        )
        dev = list(dev_ds)
        assert all(
            isinstance(b[("x",)], jax.Array) for b in dev
        )
        _batches_equal(host, _drain(iter(dev)))

    def test_device_put_pipelined_defers_source_error(self):
        """A source failure surfaces at the stream position where it
        happened: batches already staged/uploaded deliver first, then the
        error — never dropped rows, never an early misattributed raise."""
        from parquet_tpu.kernels.pipeline import device_put_pipelined

        def src():
            yield {"a": np.arange(4)}
            yield {"a": np.arange(4, 8)}
            raise RuntimeError("boom")

        got = []
        with pytest.raises(RuntimeError, match="boom"):
            for b in device_put_pipelined(src(), depth=3):
                got.append(np.asarray(b["a"]))
        assert len(got) == 2
        assert np.array_equal(got[1], np.arange(4, 8))

    def test_sharded_batches(self, pattern):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("data",))
        ds = ParquetDataset(
            pattern, batch_size=256, num_epochs=1,
            device=NamedSharding(mesh, P("data")),
        )
        b = next(iter(ds))
        assert b[("x",)].sharding.spec == P("data")


class TestReaderSatellites:
    def test_open_metadata_matches_full_open(self, pattern):
        p = sorted(glob.glob(pattern))[0]
        meta = FileReader.open_metadata(p)
        with FileReader(p) as r:
            assert meta.num_rows == r.metadata.num_rows
            # reusing the parsed footer skips the re-parse entirely
            with FileReader(p, metadata=meta) as r2:
                assert r2.num_rows == r.num_rows

    def test_open_many_and_idempotent_close(self, pattern):
        files = sorted(glob.glob(pattern))
        readers = FileReader.open_many(files)
        assert [r.num_rows for r in readers] == [
            pq.read_table(p).num_rows for p in files
        ]
        for r in readers:
            r.close()
            r.close()  # idempotent under open/close churn
        # all-or-nothing: one bad path closes the rest and raises
        with pytest.raises(FileNotFoundError):
            FileReader.open_many(files + [files[0] + ".nope"])

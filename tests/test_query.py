"""Aggregation push-down (/v1/query + `scan --aggregate`): the contracts.

Pinned here:
  * protocol: malformed aggregate specs fail with typed 400 bodies before
    any file is touched;
  * semantics: per-unit partials merged across units equal ONE whole-corpus
    pyarrow aggregation — null skipping, NaN propagation, decimal types,
    grouped and global (the differential oracle the merge rules are pinned
    against);
  * bytes: the daemon's /v1/query response, run_local_query, and
    `parquet-tool scan --aggregate` render IDENTICAL bytes;
  * bounded cardinality: group-by overflow is a typed 413, not memory
    growth;
  * admission parity with /v1/scan: the tenant byte budget charges the
    SAME plan estimate (aggregation is not a budget bypass), and
    deadline / brownout / drain produce the same typed rejections on the
    new endpoint;
  * observability: the flight record carries mask selectivity next to the
    pruning summary, and serve_aggregate_requests_total moves.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from parquet_tpu.io.source import LocalFileSource
from parquet_tpu.serve import (
    QueryRequest,
    ScanServer,
    ServeConfig,
    ServeError,
    parse_query_request,
    render_query_body,
    run_local_query,
)
from parquet_tpu.serve.protocol import DEFAULT_MAX_GROUPS
from parquet_tpu.utils import metrics

WATCHDOG_S = 30.0

ROWS_PER_FILE = 1500
GROUP = 400


def _write_corpus(d):
    rng = np.random.default_rng(41)
    base = 0
    for name in ("a.parquet", "b.parquet"):
        n = ROWS_PER_FILE
        v = rng.standard_normal(n)
        v[::17] = np.nan
        t = pa.table(
            {
                "id": pa.array(np.arange(base, base + n, dtype=np.int64)),
                "v": pa.array(
                    [None if i % 11 == 0 else float(x) for i, x in enumerate(v)],
                    pa.float64(),
                ),
                "name": pa.array([f"g{i % 7}" for i in range(n)]),
                "amount": pa.array(
                    [None if i % 13 == 0 else __import__("decimal").Decimal(i) / 4
                     for i in range(n)],
                    pa.decimal128(12, 2),
                ),
            }
        )
        pq.write_table(t, str(d / name), row_group_size=GROUP)
        base += n
    return d


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    return _write_corpus(tmp_path_factory.mktemp("query_corpus"))


@pytest.fixture()
def server(corpus):
    with ScanServer(ServeConfig(port=0, root=str(corpus))) as srv:
        yield srv.start_background()


def _post(server, route, body, headers=None, timeout=WATCHDOG_S):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=timeout)
    try:
        conn.request("POST", route, body=json.dumps(body).encode(),
                     headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _get(server, route):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=WATCHDOG_S)
    try:
        conn.request("GET", route)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _query(paths, **kw) -> QueryRequest:
    body = {"paths": paths, "aggregates": kw.pop("aggregates", ["count"]), **kw}
    return parse_query_request(json.dumps(body).encode())


def _whole_table(corpus, filters=None):
    t = pa.concat_tables(
        [pq.read_table(str(corpus / n)) for n in ("a.parquet", "b.parquet")]
    )
    if filters is not None:
        col, op, val = filters[0]
        t = t.filter({
            ">": pc.greater, ">=": pc.greater_equal, "<": pc.less,
        }[op](t.column(col), val))
    return t


# -- protocol ------------------------------------------------------------------


class TestSpec:
    @pytest.mark.parametrize(
        "body",
        [
            {"aggregates": ["count"]},  # no paths
            {"paths": "x.parquet"},  # no aggregates
            {"paths": "x.parquet", "aggregates": []},
            {"paths": "x.parquet", "aggregates": ["median"]},
            {"paths": "x.parquet", "aggregates": [["sum"]]},  # sum needs a column
            {"paths": "x.parquet", "aggregates": [{"op": "sum", "col": "v"}]},
            {"paths": "x.parquet", "aggregates": ["count"], "group_by": [1]},
            {"paths": "x.parquet", "aggregates": ["count"], "max_groups": 0},
            {"paths": "x.parquet", "aggregates": ["count"], "limit": 3},
        ],
    )
    def test_rejections_are_typed(self, body):
        with pytest.raises(ServeError) as ei:
            parse_query_request(json.dumps(body).encode())
        assert ei.value.status == 400

    def test_accepts_full_request(self):
        q = parse_query_request(json.dumps({
            "paths": ["a.parquet"],
            "filters": [["v", ">", 0]],
            "aggregates": ["count", ["sum", "v"], {"op": "min", "column": "id"}],
            "group_by": "name",
            "max_groups": 5,
            "shard": "0/2",
            "timeout_ms": 1000,
        }).encode())
        assert q.aggregates[0].op == "count" and q.aggregates[0].column is None
        assert q.aggregates[1] == ("sum", "v")
        assert q.group_by == ("name",) and q.max_groups == 5
        assert q.shard == (0, 2) and q.timeout_ms == 1000

    def test_endpoint_bad_spec_is_typed_400(self, server):
        status, _h, body = _post(
            server, "/v1/query", {"paths": "a.parquet", "aggregates": ["median"]}
        )
        assert status == 400
        assert json.loads(body)["error"]["code"] == "bad_aggregates"


# -- semantics vs the pyarrow oracle -------------------------------------------


class TestSemantics:
    def test_global_matches_pyarrow(self, corpus):
        q = _query(
            [str(corpus / "*.parquet")],
            aggregates=["count", ["count", "v"], ["sum", "v"], ["min", "v"],
                        ["max", "v"], ["sum", "amount"], ["min", "amount"]],
            filters=[["id", ">=", 100]],
        )
        got = run_local_query(q.paths, q)["result"]
        t = _whole_table(corpus, [("id", ">=", 100)])
        assert got["count"] == t.num_rows
        assert got["count(v)"] == pc.count(t.column("v")).as_py()
        # NaN propagates through sum exactly as one whole-corpus kernel
        assert np.isnan(got["sum(v)"]) == np.isnan(pc.sum(t.column("v")).as_py())
        if not np.isnan(got["sum(v)"]):
            assert abs(got["sum(v)"] - pc.sum(t.column("v")).as_py()) < 1e-9
        assert got["min(v)"] == pc.min(t.column("v")).as_py()
        assert got["max(v)"] == pc.max(t.column("v")).as_py()
        assert got["sum(amount)"] == pc.sum(t.column("amount")).as_py()
        assert got["min(amount)"] == pc.min(t.column("amount")).as_py()

    def test_group_by_matches_pyarrow(self, corpus):
        q = _query(
            [str(corpus / "*.parquet")],
            aggregates=["count", ["sum", "v"], ["min", "id"], ["max", "id"]],
            group_by=["name"],
            filters=[["v", ">", 0.0]],
        )
        got = run_local_query(q.paths, q)
        t = _whole_table(corpus, [("v", ">", 0.0)])
        ora = t.group_by(["name"]).aggregate(
            [([], "count_all"), ("v", "sum"), ("id", "min"), ("id", "max")]
        )
        assert got["group_count"] == ora.num_rows
        om = {r["key"][0]: r["aggregates"] for r in got["groups"]}
        for i in range(ora.num_rows):
            k = ora.column("name")[i].as_py()
            assert om[k]["count"] == ora.column("count_all")[i].as_py()
            assert abs(om[k]["sum(v)"] - ora.column("v_sum")[i].as_py()) < 1e-9
            assert om[k]["min(id)"] == ora.column("id_min")[i].as_py()
            assert om[k]["max(id)"] == ora.column("id_max")[i].as_py()
        # deterministic ordering: groups sort by canonical key encoding
        keys = [r["key"] for r in got["groups"]]
        assert keys == sorted(keys)

    def test_all_null_aggregates_are_null(self, tmp_path):
        p = tmp_path / "nulls.parquet"
        pq.write_table(
            pa.table({"x": pa.array([None, None], pa.int64())}), str(p)
        )
        q = _query([str(p)], aggregates=[["sum", "x"], ["min", "x"], ["count", "x"]])
        got = run_local_query(q.paths, q)["result"]
        assert got["sum(x)"] is None and got["min(x)"] is None
        assert got["count(x)"] == 0

    def test_count_star_without_filters_decodes_nothing(self, corpus):
        snap = metrics.snapshot()
        q = _query([str(corpus / "*.parquet")])
        got = run_local_query(q.paths, q)
        d = metrics.delta(snap)
        assert got["result"]["count"] == 2 * ROWS_PER_FILE
        assert got["rows_scanned"] == 2 * ROWS_PER_FILE
        # footers are read; data pages are NOT
        assert not d.get("pages_decoded_total", 0)

    def test_group_overflow_is_typed(self, corpus):
        q = _query(
            [str(corpus / "a.parquet")], aggregates=["count"],
            group_by=["name"], max_groups=3,
        )
        with pytest.raises(ServeError) as ei:
            run_local_query(q.paths, q)
        assert ei.value.status == 413 and ei.value.code == "group_overflow"

    def test_shard_partitions_units(self, corpus):
        q_full = _query([str(corpus / "*.parquet")])
        full = run_local_query(q_full.paths, q_full)
        parts = []
        for i in range(2):
            q = _query([str(corpus / "*.parquet")], shard=[i, 2])
            parts.append(run_local_query(q.paths, q))
        assert sum(p["result"]["count"] for p in parts) == full["result"]["count"]
        assert sum(p["units"] for p in parts) == full["units"]


# -- the endpoint --------------------------------------------------------------


class TestEndpoint:
    BODY = {
        "paths": "*.parquet",
        "filters": [["v", ">", 0.0]],
        "aggregates": ["count", ["sum", "v"], ["max", "id"]],
        "group_by": ["name"],
    }

    def test_daemon_bytes_match_local_twin(self, server, corpus):
        status, headers, payload = _post(server, "/v1/query", self.BODY)
        assert status == 200, payload
        assert headers.get("Content-Type") == "application/json"
        q = parse_query_request(
            json.dumps({**self.BODY, "paths": [str(corpus / "*.parquet")]}).encode()
        )
        assert payload == render_query_body(run_local_query(q.paths, q))

    def test_aggregate_metric_moves(self, server):
        snap = metrics.snapshot()
        assert _post(server, "/v1/query", self.BODY)[0] == 200
        d = metrics.delta(snap)
        assert d.get("serve_aggregate_requests_total", 0) >= 1

    def test_flight_record_carries_selectivity(self, server):
        rid = "q-selectivity-test"
        status, _h, _b = _post(
            server, "/v1/query", self.BODY, headers={"X-Request-Id": rid}
        )
        assert status == 200
        status, body = _get(server, f"/v1/debug/requests/{rid}")
        assert status == 200
        rec = json.loads(body)
        res = rec["plan"]["residual"]
        assert res["rows_scanned"] == 2 * ROWS_PER_FILE
        assert 0 < res["rows_matched"] < res["rows_scanned"]
        assert res["selectivity"] == round(
            res["rows_matched"] / res["rows_scanned"], 6
        )
        # the pruning summary is still there, NEXT to the residual stats
        assert "units_admitted" in rec["plan"]

    def test_budget_charges_plan_estimate(self, corpus):
        """Aggregation must not bypass the scanned-byte budget: /v1/query
        charges the same plan estimate /v1/scan would."""
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus),
                tenant_budget_mb=1, budget_window_s=3600.0,
            )
        ) as server:
            server.start_background()
            headers = {"X-Tenant": "alice"}
            status = None
            for _ in range(200):
                status, _h, body = _post(
                    server, "/v1/query", self.BODY, headers=headers
                )
                if status != 200:
                    break
            assert status == 429
            assert json.loads(body)["error"]["code"] == "tenant_over_budget"
            # budgets are per tenant
            s2, _h, _b = _post(
                server, "/v1/query", self.BODY, headers={"X-Tenant": "bob"}
            )
            assert s2 == 200

    def test_deadline_504_leaves_daemon_healthy(self, corpus):
        from parquet_tpu.testing.flaky import FlakySource

        slow = lambda p: FlakySource(  # noqa: E731
            LocalFileSource(p), seed=0, latency_s=0.25
        )
        with ScanServer(
            ServeConfig(
                port=0, root=str(corpus), cache_mb=0, source_factory=slow
            )
        ) as server:
            server.start_background()
            status, _h, body = _post(
                server, "/v1/query", self.BODY,
                headers={"X-Timeout-Ms": "120"},
            )
            assert status == 504
            assert json.loads(body)["error"]["code"] == "deadline_exceeded"
            assert _get(server, "/healthz")[0] == 200
            assert server.service.admission.in_flight == 0

    def test_drain_rejects_with_typed_503(self, server):
        server.service.admission.begin_drain()
        status, headers, body = _post(server, "/v1/query", self.BODY)
        assert status == 503
        assert json.loads(body)["error"]["code"] == "draining"

    def test_brownout_sheds_queries(self, corpus):
        with ScanServer(
            ServeConfig(port=0, root=str(corpus), brownout_depth=1)
        ) as server:
            server.start_background()
            # the first admission only SEEDS the brownout window's
            # baseline; the depth check applies from the second on
            assert _post(server, "/v1/query", self.BODY)[0] == 200
            metrics.set_gauge("pool_queue_depth", 5, pool="pqt-serve")
            try:
                status, headers, body = _post(server, "/v1/query", self.BODY)
                assert status == 503
                assert json.loads(body)["error"]["code"] == "brownout"
                assert "Retry-After" in headers
            finally:
                metrics.set_gauge("pool_queue_depth", 0, pool="pqt-serve")
            assert _post(server, "/v1/query", self.BODY)[0] == 200

    def test_concurrent_queries_identical(self, server, corpus):
        ref = _post(server, "/v1/query", self.BODY)[2]
        out: dict = {}

        def hammer(i):
            out[i] = _post(server, "/v1/query", self.BODY)

        threads = [threading.Thread(target=hammer, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(WATCHDOG_S)
        assert all(not t.is_alive() for t in threads)
        for i, (status, _h, payload) in out.items():
            assert status == 200 and payload == ref, i

    def test_unreadable_file_is_typed_422(self, server, corpus, tmp_path):
        bad = corpus / "bad.parquet"
        bad.write_bytes(b"PAR1garbagegarbagePAR1")
        try:
            status, _h, body = _post(
                server, "/v1/query",
                {"paths": "bad.parquet", "aggregates": ["count", ["sum", "id"]],
                 "filters": [["id", ">", 0]]},
            )
            assert status == 422
            assert json.loads(body)["error"]["code"] == "unreadable_file"
        finally:
            bad.unlink()


# -- deadline plumbing (unit level, no HTTP) -----------------------------------


class TestExecutor:
    def test_expired_deadline_is_typed(self, corpus):
        from parquet_tpu.serve.admission import Deadline
        from parquet_tpu.serve.executor import execute_query
        from parquet_tpu.serve.protocol import ScanRequest
        from parquet_tpu.serve.session import ScanSession

        q = _query([str(corpus / "*.parquet")], aggregates=[["sum", "v"]])
        session = ScanSession()
        planned = session.plan(
            ScanRequest(
                paths=q.paths, columns=["v"], filters=None, limit=None,
                format="jsonl", shard=None, timeout_ms=None,
            )
        )
        t0 = time.monotonic()
        with pytest.raises(ServeError) as ei:
            execute_query(
                planned, q, session,
                deadline=Deadline(0.0, clock=time.monotonic),
            )
        assert ei.value.status == 504
        assert time.monotonic() - t0 < WATCHDOG_S

"""BYTE_STREAM_SPLIT (encoding 9) — beyond-reference coverage.

The reference's encoding matrix stops at DELTA_BYTE_ARRAY (reference:
chunk_reader.go:41-159); BSS is the one core encoding it lacks. It is a pure
(W, n) <-> (n, W) layout transform, so decode/encode are single transposes
(ops/byte_stream_split.py) and the native chunk walk de-interleaves in C so
BSS pages keep the PLAIN device route. Cross-validated against pyarrow in
both directions over types x codecs x page versions, with nulls and FLBA.
"""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.ops.byte_stream_split import (
    decode_byte_stream_split,
    encode_byte_stream_split,
)

rng = np.random.default_rng(42)


class TestOps:
    @pytest.mark.parametrize(
        "ptype,arr",
        [
            (Type.FLOAT, rng.standard_normal(1001).astype(np.float32)),
            (Type.DOUBLE, rng.standard_normal(1001)),
            (Type.INT32, rng.integers(-(2**31), 2**31, 997).astype(np.int32)),
            (Type.INT64, rng.integers(-(2**62), 2**62, 997)),
        ],
    )
    def test_roundtrip(self, ptype, arr):
        enc = encode_byte_stream_split(arr, ptype)
        assert len(enc) == arr.nbytes
        out = decode_byte_stream_split(enc, len(arr), ptype)
        np.testing.assert_array_equal(out, arr)
        # spec layout: first n bytes are the byte-0 stream
        lane0 = arr.view(np.uint8).reshape(len(arr), -1)[:, 0]
        np.testing.assert_array_equal(
            np.frombuffer(enc[: len(arr)], dtype=np.uint8), lane0
        )

    def test_flba(self):
        rows = rng.integers(0, 256, (321, 5), dtype=np.uint8)
        enc = encode_byte_stream_split(rows, Type.FIXED_LEN_BYTE_ARRAY, 5)
        out = decode_byte_stream_split(enc, 321, Type.FIXED_LEN_BYTE_ARRAY, 5)
        np.testing.assert_array_equal(out, rows)

    def test_errors(self):
        with pytest.raises(ValueError):
            decode_byte_stream_split(b"\x00" * 8, 4, Type.FLOAT)  # short
        with pytest.raises(ValueError):
            decode_byte_stream_split(b"", 1, Type.BYTE_ARRAY)  # bad type
        assert decode_byte_stream_split(b"", 0, Type.DOUBLE).shape == (0,)


ALL_COLS = ("f", "d", "i", "l")


def _table(n=20_000):
    return pa.table(
        {
            "f": pa.array(rng.standard_normal(n).astype(np.float32)),
            "d": pa.array(rng.standard_normal(n)),
            "i": pa.array(rng.integers(-(2**31), 2**31, n).astype(np.int32)),
            "l": pa.array(rng.integers(-(2**62), 2**62, n)),
        }
    )


class TestPyarrowToOurs:
    @pytest.mark.parametrize("codec", ["none", "snappy", "zstd", "lz4"])
    @pytest.mark.parametrize("pagever", ["1.0", "2.0"])
    def test_matrix(self, codec, pagever):
        t = _table()
        buf = io.BytesIO()
        pq.write_table(
            t,
            buf,
            use_dictionary=False,
            compression=codec,
            data_page_version=pagever,
            version="2.6",
            column_encoding={c: "BYTE_STREAM_SPLIT" for c in ALL_COLS},
        )
        for backend in ("host", "tpu_roundtrip"):
            buf.seek(0)
            with FileReader(buf, backend=backend) as r:
                cd = r.read_row_group(0)
                for c in ALL_COLS:
                    np.testing.assert_array_equal(
                        np.asarray(cd[(c,)].values), np.asarray(t.column(c))
                    )

    def test_nullable_bss(self):
        vals = [None if i % 7 == 0 else float(i) for i in range(5_000)]
        t = pa.table({"x": pa.array(vals, pa.float64())})
        buf = io.BytesIO()
        pq.write_table(
            t,
            buf,
            use_dictionary=False,
            compression="snappy",
            column_encoding={"x": "BYTE_STREAM_SPLIT"},
        )
        for backend in ("host", "tpu_roundtrip"):
            buf.seek(0)
            with FileReader(buf, backend=backend) as r:
                assert [row["x"] for row in r.iter_rows()] == vals

    def test_device_batches(self):
        t = _table(8_192)
        buf = io.BytesIO()
        pq.write_table(
            t,
            buf,
            use_dictionary=False,
            compression="zstd",
            column_encoding={c: "BYTE_STREAM_SPLIT" for c in ALL_COLS},
        )
        buf.seek(0)
        with FileReader(buf) as r:
            b = next(r.iter_device_batches(4_096))
            np.testing.assert_array_equal(
                np.asarray(b[("l",)]), np.asarray(t.column("l"))[:4_096]
            )


class TestOursToPyarrow:
    @pytest.mark.parametrize("version", [1, 2])
    def test_matrix(self, version):
        t = _table(5_000)
        schema = parse_schema(
            "message m { required float f; required double d; "
            "required int32 i; required int64 l; }"
        )
        out = io.BytesIO()
        with FileWriter(
            out,
            schema,
            codec="snappy",
            data_page_version=version,
            column_encodings={c: "BYTE_STREAM_SPLIT" for c in ALL_COLS},
        ) as w:
            for c in ALL_COLS:
                w.write_column(c, t.column(c))
        out.seek(0)
        back = pq.read_table(out)
        for c in ALL_COLS:
            np.testing.assert_array_equal(
                np.asarray(back.column(c)), np.asarray(t.column(c))
            )

    def test_flba_to_pyarrow(self):
        rows = [bytes([i % 256] * 6) for i in range(2_000)]
        schema = parse_schema(
            "message m { required fixed_len_byte_array(6) a; }"
        )
        out = io.BytesIO()
        with FileWriter(
            out, schema, column_encodings={"a": "BYTE_STREAM_SPLIT"}
        ) as w:
            w.write_column("a", rows)
        out.seek(0)
        assert pq.read_table(out).column("a").to_pylist() == rows

    def test_own_roundtrip_bss_pages_multipage(self):
        arr = rng.standard_normal(300_000)  # several 1MiB pages
        schema = parse_schema("message m { required double x; }")
        out = io.BytesIO()
        with FileWriter(
            out, schema, codec="gzip", column_encodings={"x": "BYTE_STREAM_SPLIT"}
        ) as w:
            w.write_column("x", arr)
        out.seek(0)
        with FileReader(out) as r:
            np.testing.assert_array_equal(r.read_row_group(0)[("x",)].values, arr)

    def test_fixed_list_input_validation(self):
        # review regressions: wrong-sized elements summing to n*width, and
        # mixed types, must both raise StoreError — never silently re-split
        schema = parse_schema("message m { required fixed_len_byte_array(4) a; }")
        for bad in ([b"12", b"123456"], [b"1234", "abcd"]):
            with pytest.raises(ValueError, match="4"):
                with FileWriter(io.BytesIO(), schema) as w:
                    w.write_column("a", bad)
                    w.flush_row_group()

    def test_rejected_for_byte_array(self):
        schema = parse_schema("message m { required binary s (UTF8); }")
        with pytest.raises(ValueError, match="BYTE_STREAM_SPLIT"):
            FileWriter(
                io.BytesIO(), schema, column_encodings={"s": "BYTE_STREAM_SPLIT"}
            )


class TestDeviceTranspose:
    """4-byte BSS pages ship their streams RAW and transpose ON DEVICE
    (kernels/device_ops.bss_transpose_device); 8-byte types keep the host
    de-interleave (no u8x8 bitcast in the TPU x64 emulation)."""

    def test_four_byte_pages_take_the_bss_route(self, tmp_path):
        from parquet_tpu.core.chunk import ChunkWindow, chunk_byte_range
        from parquet_tpu.kernels.pipeline import prepare_chunk_plan

        t = _table(50_000)
        path = str(tmp_path / "bss_route.parquet")
        pq.write_table(
            t, path, use_dictionary=False, compression="snappy",
            version="2.6",
            column_encoding={c: "BYTE_STREAM_SPLIT" for c in ALL_COLS},
        )
        kinds = {}
        with FileReader(path) as r:
            for p, cc, col in r._selected_chunks(0):
                off, tot = chunk_byte_range(cc)
                plan = prepare_chunk_plan(
                    ChunkWindow(r._pread(off, tot), off), cc, col
                )
                kinds[p[0]] = {
                    k for _, _, _, k, _ in plan.page_infos if k != "empty"
                }
                # deliver through the device path and check values
                dc = plan.dispatch_device().device_column()
                np.testing.assert_array_equal(
                    np.asarray(dc.values), np.asarray(t.column(p[0]))
                )
        assert kinds["f"] == {"bss"}, kinds  # float32: device transpose
        assert kinds["i"] == {"bss"}, kinds  # int32: device transpose
        assert kinds["d"] == {"values"}, kinds  # float64: host de-interleave
        assert kinds["l"] == {"values"}, kinds  # int64: host de-interleave

"""Property-based roundtrips: random schemas x data x writer options.

Single-feature suites can miss cross-feature interactions (BSS under a page
index, blooms on nullable dictionary chunks, CRC + V2 + zstd, ...). Here a
seeded generator draws a schema, data with nulls, and a writer-option combo;
every draw must (a) read back exactly through our reader, (b) read back
exactly through pyarrow (cross-implementation), and (c) decode byte-identical
on the device roundtrip backend. Failures reproduce from the printed seed.
"""

import datetime as _rt_dt
import decimal as _rt_dec
import math

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import (
    date,
    decimal as decimal_spec,
    group,
    int_type,
    list_of,
    message,
    optional,
    required,
    string,
    time_of_day,
    timestamp,
)

N_SEEDS = 12
N_ROWS = 700


def eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(eq(x, y) for x, y in zip(a, b))
    return a == b


_EPOCH = _rt_dt.datetime(1970, 1, 1, tzinfo=_rt_dt.timezone.utc)

_SCALARS = [
    ("i32", Type.INT32, lambda r: int(r.integers(-(2**31), 2**31))),
    ("i64", Type.INT64, lambda r: int(r.integers(-(2**62), 2**62))),
    ("f32", Type.FLOAT, lambda r: float(np.float32(r.standard_normal()))),
    ("f64", Type.DOUBLE, lambda r: float(r.standard_normal())),
    ("flag", Type.BOOLEAN, lambda r: bool(r.random() < 0.5)),
    ("name", "string", lambda r: f"s{int(r.integers(0, 50))}" * int(r.integers(1, 3))),
    # logical types: generators emit the ROW-DOMAIN values iter_rows
    # returns, so the roundtrip exercises both conversion directions
    ("ts", "timestamp",
     lambda r: _EPOCH + _rt_dt.timedelta(microseconds=int(r.integers(-2**52, 2**52)))),
    ("day", "date",
     lambda r: _rt_dt.date(1970, 1, 1) + _rt_dt.timedelta(days=int(r.integers(-200_000, 200_000)))),
    ("amount", "decimal",
     lambda r: _rt_dec.Decimal(int(r.integers(-10**8, 10**8))).scaleb(-2)),
    ("u64", "uint64", lambda r: int(r.integers(0, 2**63)) * 2 + int(r.random() < 0.5)),
    ("tod", "time", lambda r: _rt_dt.time(
        int(r.integers(0, 24)), int(r.integers(0, 60)), int(r.integers(0, 60)),
        int(r.integers(0, 1000)) * 1000,  # whole millis: exact at both units
    )),
]

_LOGICAL_SPECS = {
    # utc=True always: the generators emit tz-aware datetimes, and the
    # read side returns naive ones for utc=False specs (spec semantics)
    "timestamp": lambda r: timestamp("micros", utc=True),
    "date": lambda r: date(),
    "decimal": lambda r: decimal_spec(
        10, 2, fixed_width=9 if r.random() < 0.3 else None
    ),
    "uint64": lambda r: int_type(64, signed=False),
    "time": lambda r: time_of_day(
        "millis" if r.random() < 0.5 else "micros", utc=True
    ),
}


def _draw_schema_and_rows(rng):
    fields = []
    gens = []
    n_cols = int(rng.integers(2, 6))
    picks = rng.choice(len(_SCALARS), size=n_cols, replace=True)
    for ci, pi in enumerate(picks):
        base, ptype, gen = _SCALARS[pi]
        colname = f"{base}_{ci}"
        opt = bool(rng.random() < 0.5)
        if ptype == "string":
            spec = string()
        elif ptype in _LOGICAL_SPECS:
            spec = _LOGICAL_SPECS[ptype](rng)
        else:
            spec = ptype
        fields.append(optional(colname, spec) if opt else required(colname, spec))
        null_p = 0.2 if opt else 0.0
        gens.append((colname, gen, null_p))
    if rng.random() < 0.5:
        fields.append(list_of("tags", optional("element", Type.INT32)))
        gens.append(
            (
                "tags",
                lambda r: [
                    None if r.random() < 0.1 else int(r.integers(0, 100))
                    for _ in range(int(r.integers(0, 5)))
                ],
                0.15,
            )
        )
    if rng.random() < 0.4:
        fields.append(
            group(
                "meta",
                required("k", Type.INT64),
                optional("v", string()),
            )
        )
        gens.append(
            (
                "meta",
                lambda r: {
                    "k": int(r.integers(0, 1000)),
                    "v": None if r.random() < 0.3 else f"m{int(r.integers(0, 9))}",
                },
                0.2,
            )
        )
    schema = message(*fields)
    rows = []
    for _ in range(N_ROWS):
        row = {}
        for colname, gen, null_p in gens:
            row[colname] = None if rng.random() < null_p else gen(rng)
        rows.append(row)
    return schema, rows


def _draw_options(rng, schema):
    opts = {
        "codec": str(
            rng.choice(["uncompressed", "snappy", "gzip", "zstd", "lz4", "brotli"])
        ),
        "data_page_version": int(rng.choice([1, 2])),
        "max_page_size": int(rng.choice([512, 4096, 1 << 20])),
        "enable_dictionary": bool(rng.random() < 0.7),
        "with_crc": bool(rng.random() < 0.3),
        "write_page_index": bool(rng.random() < 0.5),
    }
    leaves = [leaf for leaf in schema.leaves]
    bloomable = [
        leaf.path_str
        for leaf in leaves
        if leaf.type != Type.BOOLEAN and leaf.max_rep == 0 and rng.random() < 0.3
    ]
    if bloomable:
        opts["bloom_filters"] = bloomable
    encodings = {}
    for leaf in leaves:
        if leaf.max_rep > 0 or rng.random() > 0.3:
            continue
        if leaf.type in (Type.INT32, Type.INT64):
            encodings[leaf.path_str] = str(
                rng.choice(["DELTA_BINARY_PACKED", "BYTE_STREAM_SPLIT"])
            )
        elif leaf.type in (Type.FLOAT, Type.DOUBLE):
            encodings[leaf.path_str] = "BYTE_STREAM_SPLIT"
    if encodings:
        opts["column_encodings"] = encodings
    return opts


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_roundtrip(tmp_path, seed):
    rng = np.random.default_rng(1000 + seed)
    schema, rows = _draw_schema_and_rows(rng)
    opts = _draw_options(rng, schema)
    path = str(tmp_path / f"prop_{seed}.parquet")
    with FileWriter(path, schema, **opts) as w:
        n_groups = int(rng.choice([1, 3]))
        per = (len(rows) + n_groups - 1) // n_groups
        for g in range(n_groups):
            w.write_rows(rows[g * per : (g + 1) * per])
            w.flush_row_group()
    # (a) our reader returns the input exactly (compact_levels randomly on:
    # bit-packed level storage must be invisible to every consumer)
    with FileReader(
        path,
        validate_crc=opts["with_crc"],
        compact_levels=bool(rng.random() < 0.5),
    ) as r:
        ours = list(r.iter_rows())
    assert len(ours) == len(rows), (seed, opts)
    for i, (o, exp) in enumerate(zip(ours, rows)):
        assert eq(o, exp), (seed, i, o, exp, opts)
    # (b) pyarrow agrees (cross-implementation)
    theirs = pq.read_table(path).to_pylist()
    for i, (t, exp) in enumerate(zip(theirs, rows)):
        assert eq(t, exp), (seed, i, t, exp, opts)
    # (c) the device roundtrip backend is byte-identical to the host
    from tests.test_tpu_backend import both_backends

    both_backends(path)
    # (d) when a predicate applies, the pruning stack agrees with brute force
    int_leaves = [
        leaf for leaf in schema.leaves
        if leaf.type == Type.INT64 and leaf.max_rep == 0 and len(leaf.path) == 1
    ]
    if int_leaves:
        name = int_leaves[0].name
        pivot = next((row[name] for row in rows if row[name] is not None), None)
        if pivot is not None:
            with FileReader(path) as r:
                got = [row[name] for row in r.iter_rows(filters=[(name, ">=", pivot)])]
            expect = [
                row[name]
                for row in rows
                if row[name] is not None and row[name] >= pivot
            ]
            assert got == expect, (seed, name, pivot, opts)

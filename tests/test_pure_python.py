"""Pure-Python fallback: everything must work without ANY native code.

The C++ helper library and the CPython extension are deliberate
accelerators, not dependencies — the Python paths are the error-semantics
oracle the native walk falls back to. This suite disables both (and rebuilds
the codec registry so snappy/lz4 resolve to pyarrow's implementations) and
drives a representative end-to-end matrix: write with dictionaries, delta,
page index and bloom filters (pure-Python XXH64); read rows, filters, and
the device roundtrip backend through the per-page Python walk.
"""

import contextlib

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, parse_schema
from parquet_tpu.meta.parquet_types import Type


@contextlib.contextmanager
def _no_native(monkeypatch):
    from parquet_tpu.core import arrays, assembly_vec, column_store, compress
    from parquet_tpu.utils import native as nat

    monkeypatch.setattr(nat, "_cached", None)
    monkeypatch.setattr(nat, "_probed", True)
    for mod in (arrays, assembly_vec, column_store):
        monkeypatch.setattr(mod, "_ext", None)
    saved = dict(compress._REGISTRY)
    compress._REGISTRY.clear()
    compress._init_registry()
    try:
        assert nat.get_native() is None
        yield
    finally:
        compress._REGISTRY.clear()
        compress._REGISTRY.update(saved)


@pytest.mark.parametrize("codec", ["snappy", "gzip", "zstd", "lz4_raw"])
def test_end_to_end_without_native(tmp_path, monkeypatch, codec):
    with _no_native(monkeypatch):
        from parquet_tpu.core.compress import _REGISTRY, _NativeLz4Raw, _NativeSnappy

        assert not any(
            isinstance(c, (_NativeSnappy, _NativeLz4Raw)) for c in _REGISTRY.values()
        )
        schema = parse_schema(
            "message m { required int64 id; optional binary s (UTF8); "
            "required int64 ts (TIMESTAMP_MICROS); }"
        )
        n = 3_000
        rows = [
            {
                "id": i,
                "s": None if i % 11 == 0 else f"u{i % 41}",
                "ts": 1_700_000_000_000_000 + i,
            }
            for i in range(n)
        ]
        path = str(tmp_path / f"nonative_{codec}.parquet")
        with FileWriter(
            path,
            schema,
            codec=codec,
            max_page_size=2_048,
            write_page_index=True,
            bloom_filters=["id"],
            column_encodings={"ts": "DELTA_BINARY_PACKED"},
        ) as w:
            w.write_rows(rows)
        # pyarrow (fully independent) reads the pure-Python-written file
        got = pq.read_table(path)
        assert got.column("id").to_pylist() == [r["id"] for r in rows]
        assert got.column("s").to_pylist() == [r["s"] for r in rows]
        # our reader, still without native: rows, filters, bloom, page index
        with FileReader(path) as r:
            assert list(r.iter_rows()) != []
            assert [row["id"] for row in r.iter_rows(filters=[("id", "==", 77)])] == [77]
            assert list(r.iter_rows(filters=[("id", "==", n + 5)])) == []
            bf = r.read_bloom_filter(0, "id")
            assert bf is not None and bf.might_contain(Type.INT64, 77)
            ci, oi = r.read_page_index(0)[("id",)]
            assert ci is not None and oi is not None
        # device roundtrip parity rides the per-page Python walk
        with FileReader(path, backend="tpu_roundtrip") as r:
            cd = r.read_row_group(0)[("id",)]
            np.testing.assert_array_equal(
                np.asarray(cd.values), np.arange(n, dtype=np.int64)
            )


def test_pyarrow_written_file_without_native(tmp_path, monkeypatch):
    import pyarrow as pa

    t = pa.table(
        {
            "x": pa.array(range(5_000), pa.int64()),
            "tags": pa.array(
                [None if i % 9 == 0 else [i % 5, i % 7] for i in range(5_000)],
                pa.list_(pa.int32()),
            ),
        }
    )
    path = str(tmp_path / "pa_nonative.parquet")
    pq.write_table(t, path, compression="snappy", row_group_size=2_000)
    with _no_native(monkeypatch):
        with FileReader(path) as r:
            assert list(r.iter_rows()) == t.to_pylist()

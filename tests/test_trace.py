"""Span tracer tests: contextvar isolation under threads, lock-protected
merge exactness, pool attribution under the prepare pool, report() ordering,
Chrome trace-event schema, and the zero-overhead (no span allocations when
inactive) guarantee."""

import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import pytest

from parquet_tpu.core.reader import FileReader
from parquet_tpu.core.writer import FileWriter
from parquet_tpu.meta.parquet_types import Type
from parquet_tpu.schema.builder import message, optional, required, string
from parquet_tpu.utils import trace as trace_mod
from parquet_tpu.utils.trace import (
    add_seconds,
    add_seconds_batch,
    bump,
    decode_trace,
    span,
    stage,
    traced_submit,
)


def _write_sample(path: str, rows: int = 4000, groups: int = 2) -> str:
    schema = message(required("id", Type.INT64), optional("name", string()))
    with FileWriter(path, schema, codec="snappy") as w:
        for g in range(groups):
            w.write_rows(
                {
                    "id": int(g * rows + i),
                    "name": f"g{g}n{i % 53}" if i % 7 else None,
                }
                for i in range(rows)
            )
            w.flush_row_group()
    return path


@pytest.fixture(scope="module")
def sample(tmp_path_factory):
    return _write_sample(str(tmp_path_factory.mktemp("trace") / "t.parquet"))


def _traced_read_totals(path) -> dict:
    """{stage name: (bytes, calls)} of one fully traced host read."""
    with decode_trace() as t:
        with FileReader(path) as r:
            for i in range(r.num_row_groups):
                r.read_row_group(i)
    return {name: (s.bytes, s.calls) for name, s in t.stages.items()}


class TestThreadSafety:
    def test_eight_thread_hammer_exact_byte_totals(self, sample):
        """Regression for the pre-contextvar bug: nested decode_trace() from
        two threads clobbered the module-global and corrupted byte totals.
        Eight threads each trace their own read; every trace must hold the
        EXACT solo totals (bytes and call counts, which are deterministic —
        seconds are not)."""
        expected = _traced_read_totals(sample)
        assert expected, "solo traced read collected nothing"
        assert any(b for b, _ in expected.values()), "no byte totals collected"

        barrier = threading.Barrier(8)
        results: list = [None] * 8
        errors: list = []

        def worker(k):
            try:
                barrier.wait()
                results[k] = _traced_read_totals(sample)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors
        for k, got in enumerate(results):
            assert got == expected, f"thread {k} totals diverged: {got}"

    def test_shared_trace_concurrent_merge_exact(self):
        """Many threads merging into ONE trace (the pool-worker shape): the
        lock-protected merge must lose nothing."""
        n_threads, n_iter = 8, 5000
        with decode_trace() as t:
            with ThreadPoolExecutor(max_workers=n_threads) as pool:

                def hammer():
                    for _ in range(n_iter):
                        bump("hammer", 3)

                futs = [traced_submit(pool, hammer) for _ in range(n_threads)]
                for f in futs:
                    f.result()
        s = t.stages["hammer"]
        assert s.calls == n_threads * n_iter
        assert s.bytes == 3 * n_threads * n_iter

    def test_concurrent_traces_do_not_cross_attribute(
        self, sample, tmp_path, monkeypatch
    ):
        """Two traced roundtrip reads sharing a 16-thread prepare pool: each
        trace must account exactly its own file's chunks (the explicit
        copy_context carry into pool workers), not a mix."""
        import parquet_tpu.core.reader as reader_mod

        # force the full-width pool regardless of host core count
        monkeypatch.setenv("PQT_HOST_THREADS", "16")
        pool = ThreadPoolExecutor(max_workers=16, thread_name_prefix="pqt-host")
        monkeypatch.setattr(reader_mod, "_pool", pool)
        small = _write_sample(str(tmp_path / "small.parquet"), rows=500, groups=1)

        def chunk_events(path):
            with decode_trace() as t:
                with FileReader(path, backend="tpu_roundtrip") as r:
                    for i in range(r.num_row_groups):
                        r.read_row_group(i)
            c = t.counters()
            # every chunk prepared lands on exactly one ladder rung
            return (
                c.get("prepare_fused_engaged", 0)
                + c.get("prepare_fused_declined", 0)
                + c.get("prepare_staged_chunk", 0)
            )

        expected_big = chunk_events(sample)  # 2 groups x 2 cols = 4 chunks
        expected_small = chunk_events(small)  # 1 group x 2 cols = 2 chunks
        assert expected_big == 4 and expected_small == 2

        barrier = threading.Barrier(2)
        out: dict = {}

        def run(name, path):
            barrier.wait()
            out[name] = chunk_events(path)

        a = threading.Thread(target=run, args=("big", sample))
        b = threading.Thread(target=run, args=("small", small))
        a.start(); b.start(); a.join(); b.join()
        pool.shutdown(wait=True)
        assert out == {"big": expected_big, "small": expected_small}


class TestReport:
    def test_sort_time_default_and_total_footer(self):
        with decode_trace() as t:
            add_seconds("zz_slow", 0.2, 1000)
            add_seconds("aa_fast", 0.01, 50)
        rep = t.report()
        lines = rep.splitlines()
        assert lines[-1].startswith("TOTAL")
        assert lines.index([x for x in lines if x.startswith("zz_slow")][0]) < \
            lines.index([x for x in lines if x.startswith("aa_fast")][0])
        # TOTAL sums seconds/bytes/calls
        assert "1,050 B" in lines[-1]

    def test_sort_name(self):
        with decode_trace() as t:
            add_seconds("zz_slow", 0.2)
            add_seconds("aa_fast", 0.01)
        lines = t.report(sort="name").splitlines()
        assert lines[0].startswith("aa_fast")
        assert lines[1].startswith("zz_slow")

    def test_bad_sort_raises(self):
        with decode_trace() as t:
            pass
        with pytest.raises(ValueError):
            t.report(sort="bytes")


class TestExclusiveRollup:
    """Sub-clock seconds count ONCE in rollups: a stage (or an
    add_seconds/add_seconds_batch credit) committed inside another open
    stage aggregate is part of that parent's wall time — before this fix
    the report TOTAL and the flight-recorder rollup double-counted the
    native prepare.* split against its measured parent, and every inner
    decode stage against serve.execute."""

    def test_golden_subclock_total(self):
        """The golden pin: deterministic sub-clock credits inside a
        measured parent leave TOTAL == exclusive wall, exactly."""
        with decode_trace() as t:
            add_seconds("standalone", 0.1)  # no parent open: exclusive
            with stage("parent"):
                add_seconds_batch(
                    [("prepare.decompress", 0.04), ("prepare.levels", 0.01)]
                )
                add_seconds("prepare.crc", 0.02)
        rollup = t.stage_rollup()
        # the sub-clocks carry their nested share; the exclusive stages
        # carry none
        assert rollup["prepare.decompress"]["nested_seconds"] == 0.04
        assert rollup["prepare.levels"]["nested_seconds"] == 0.01
        assert rollup["prepare.crc"]["nested_seconds"] == 0.02
        assert "nested_seconds" not in rollup["standalone"]
        assert "nested_seconds" not in rollup["parent"]
        expect = 0.1 + rollup["parent"]["seconds"]
        assert abs(t.exclusive_seconds() - expect) < 1e-9
        # the report TOTAL footer is the exclusive sum, not the inflated
        # inclusive one (which would be expect + 0.07)
        total_line = [
            ln for ln in t.report().splitlines() if ln.startswith("TOTAL")
        ][0]
        total_ms = float(total_line.split()[1])
        assert total_ms == pytest.approx(expect * 1e3, abs=0.05)
        # sub-clocked stages are marked; the parent is not
        rep = t.report()
        assert any(
            ln.startswith("prepare.decompress") and ln.endswith("*")
            for ln in rep.splitlines()
        )
        assert "(* partly sub-clocked" in rep

    def test_nested_stage_counts_once(self):
        """The serve shape: inner decode stages under serve.execute."""
        with decode_trace() as t:
            with stage("serve.execute"):
                with stage("decompress"):
                    pass
                with stage("decode"):
                    pass
        r = t.stage_rollup()
        assert r["decompress"]["nested_seconds"] == r["decompress"]["seconds"]
        assert r["decode"]["nested_seconds"] == r["decode"]["seconds"]
        assert "nested_seconds" not in r["serve.execute"]
        assert t.exclusive_seconds() == pytest.approx(
            r["serve.execute"]["seconds"], abs=1e-9
        )

    def test_same_stage_nested_and_free_splits(self):
        """One name used both inside and outside a parent: only the
        nested share is excluded from the exclusive total."""
        with decode_trace() as t:
            add_seconds("io", 0.05)  # free-standing
            with stage("serve.execute"):
                add_seconds("io", 0.03)  # nested
        r = t.stage_rollup()
        assert r["io"]["seconds"] == pytest.approx(0.08)
        assert r["io"]["nested_seconds"] == pytest.approx(0.03)
        assert t.exclusive_seconds() == pytest.approx(
            0.05 + r["serve.execute"]["seconds"], abs=1e-9
        )

    def test_span_is_not_a_parent(self):
        """A pure hierarchy span bills no seconds, so sub-clocks inside
        it (the fused native walk under the chunk.prepare span) must stay
        EXCLUSIVE — excluding them would undercount the total."""
        with decode_trace() as t:
            with span("chunk.prepare"):
                add_seconds_batch([("prepare.decompress", 0.04)])
        r = t.stage_rollup()
        assert "nested_seconds" not in r["prepare.decompress"]
        assert t.exclusive_seconds() == pytest.approx(0.04)

    def test_nesting_carries_into_pool_workers(self):
        """instrumented_submit/traced_submit carry the open-stage depth
        with the context: work a stage submits bills as nested on the
        worker."""
        pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="pqt-test")
        try:
            with decode_trace() as t:
                with stage("serve.execute"):
                    traced_submit(
                        pool, lambda: add_seconds("io", 0.02)
                    ).result(timeout=10)
        finally:
            pool.shutdown(wait=True)
        r = t.stage_rollup()
        assert r["io"]["nested_seconds"] == pytest.approx(0.02)


def _check_event_schema(events):
    assert events, "no trace events"
    for ev in events:
        for key in ("ph", "ts", "dur", "pid", "tid", "name"):
            assert key in ev, (key, ev)
        assert ev["ph"] in ("X", "M")
        assert ev["ts"] >= 0 and ev["dur"] >= 0
        assert ev["pid"] == os.getpid()


def _check_nesting(events):
    """Complete events on one thread lane must nest or be disjoint."""
    xs = [e for e in events if e["ph"] == "X"]
    for tid in {e["tid"] for e in xs}:
        lane = sorted(
            (e for e in xs if e["tid"] == tid), key=lambda e: (e["ts"], -e["dur"])
        )
        stack = []  # open interval end times
        for e in lane:
            start, end = e["ts"], e["ts"] + e["dur"]
            while stack and start >= stack[-1] - 1e-6:
                stack.pop()
            if stack:
                assert end <= stack[-1] + 1e-3, (e, stack[-1])
            stack.append(end)


class TestChromeTrace:
    def test_schema_host_path(self, sample):
        with decode_trace() as t, span("file", {"path": sample}):
            with FileReader(sample) as r:
                for i in range(r.num_row_groups):
                    r.read_row_group(i)
        doc = t.to_chrome_trace()
        # valid JSON end to end
        doc = json.loads(json.dumps(doc))
        events = doc["traceEvents"]
        _check_event_schema(events)
        _check_nesting(events)
        names = {e["name"] for e in events}
        # the hierarchy levels all present
        for expected in ("file", "row_group", "chunk", "page", "decode_trace"):
            assert expected in names, names
        # stage leaves under them
        assert names & {"io", "decompress", "decode"}
        # thread lanes are named
        assert any(
            e["ph"] == "M" and e["name"] == "thread_name" for e in events
        )
        assert doc["otherData"]["stages"]

    def test_schema_device_pipeline_lanes_and_native_substages(self, sample):
        """The device-plan path: spans must land on the REAL worker threads
        (pqt-host/pqt-dispatch lanes) and, when the fused native walk ran,
        its internal sub-stage clocks must appear as nested spans."""
        with decode_trace() as t, span("file", {"path": sample}):
            with FileReader(sample, backend="tpu_roundtrip") as r:
                for i in range(r.num_row_groups):
                    r.read_row_group(i)
        doc = t.to_chrome_trace()
        events = doc["traceEvents"]
        _check_event_schema(events)
        _check_nesting(events)
        names = {e["name"] for e in events}
        assert "chunk.prepare" in names
        assert "dispatch" in names
        lanes = {
            e["args"]["name"] for e in events if e["ph"] == "M"
        }
        assert any(name.startswith("pqt-dispatch") for name in lanes), lanes
        if t.counters().get("prepare_fused_engaged"):
            assert any(n.startswith("prepare.") for n in names), names
            # the sub-stage spans nest inside their chunk.prepare span
            preps = [e for e in events if e["name"] == "chunk.prepare"]
            subs = [e for e in events if e["name"].startswith("prepare.")]
            for s in subs:
                assert any(
                    p["tid"] == s["tid"]
                    and p["ts"] <= s["ts"] + 1e-3
                    and s["ts"] + s["dur"] <= p["ts"] + p["dur"] + 1e-3
                    for p in preps
                ), s

    def test_add_seconds_batch_lays_spans_back_to_back(self):
        import time

        with decode_trace() as t:
            with span("outer"):
                # the batch's seconds must fit inside the enclosing span's
                # real elapsed time (as the native walk's sub-clocks do)
                time.sleep(0.006)
                add_seconds_batch([("a", 0.001), ("b", 0.002)])
        evs = [e for e in t.to_chrome_trace()["traceEvents"] if e["ph"] == "X"]
        by = {e["name"]: e for e in evs}
        a, b, outer = by["a"], by["b"], by["outer"]
        assert abs((a["ts"] + a["dur"]) - b["ts"]) < 1e-3  # contiguous
        assert outer["ts"] <= a["ts"] and b["ts"] + b["dur"] <= outer["ts"] + outer["dur"]
        assert t.stages["a"].calls == 1 and t.stages["b"].calls == 1

    def test_write_chrome_trace_file(self, sample, tmp_path):
        out = tmp_path / "trace.json"
        with decode_trace() as t:
            with FileReader(sample) as r:
                r.read_row_group(0)
        t.write_chrome_trace(str(out))
        doc = json.loads(out.read_text())
        assert doc["traceEvents"]


class TestZeroOverhead:
    def test_untraced_read_allocates_no_spans(self, sample):
        """The inactive-trace guarantee, asserted via counter (not timing):
        a read with no decode_trace active must not allocate span events."""
        # warm every lazy path first (imports, native load)
        with FileReader(sample) as r:
            r.read_row_group(0)
        before = trace_mod.span_allocations()
        with FileReader(sample) as r:
            for i in range(r.num_row_groups):
                r.read_row_group(i)
            list(r.iter_rows(row_groups=[0]))
        assert trace_mod.span_allocations() == before

    def test_stage_and_span_noop_without_trace(self):
        before = trace_mod.span_allocations()
        with stage("nothing", 10):
            pass
        with span("nothing"):
            pass
        assert trace_mod.span_allocations() == before
        assert not trace_mod.active()


class TestEventCap:
    def test_span_cap_drops_events_but_keeps_aggregates(self, monkeypatch):
        monkeypatch.setattr(trace_mod, "_MAX_EVENTS", 16)
        with decode_trace() as t:
            for _ in range(50):
                with stage("tick"):
                    pass
        assert t.stages["tick"].calls == 50  # aggregates exact past the cap
        assert t.events_dropped > 0
        assert len(t.to_chrome_trace()["traceEvents"]) <= 16 + 1  # + thread M

"""Row-group-level merge (core/merge.py): byte-verbatim compaction.

Chunk bytes copy unmodified — only footer offsets rewrite — so the merged
file must decode identically to the concatenation of its inputs, through
pyarrow (the independent oracle), our host path, and the device backend.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import FileReader, FileWriter, merge_files, parse_schema
from parquet_tpu.meta import ParquetFileError
from parquet_tpu.tools.parquet_tool import main as tool_main


def _make(path, lo, hi, **write_opts):
    t = pa.table({
        "i": pa.array(np.arange(lo, hi, dtype=np.int64)),
        "s": pa.array([f"n{k % 37}" for k in range(lo, hi)]),
        "l": pa.array(
            [None if k % 11 == 0 else [k, k + 1] for k in range(lo, hi)],
            pa.list_(pa.int32()),
        ),
    })
    pq.write_table(t, path, **write_opts)
    return t


class TestMerge:
    def test_merge_pyarrow_inputs(self, tmp_path):
        p1, p2, p3 = (str(tmp_path / f"in{k}.parquet") for k in range(3))
        t1 = _make(p1, 0, 5_000, compression="snappy", row_group_size=2_000)
        t2 = _make(p2, 5_000, 6_500, compression="snappy", use_dictionary=["s"])
        t3 = _make(p3, 6_500, 6_700, compression="snappy")
        out = str(tmp_path / "merged.parquet")
        meta = merge_files(out, [p1, p2, p3])
        want = pa.concat_tables([t1, t2, t3])
        assert meta.num_rows == want.num_rows
        # pyarrow (independent) decodes the merged bytes
        got = pq.read_table(out)
        for c in want.column_names:
            assert got.column(c).to_pylist() == want.column(c).to_pylist(), c
        # both our backends agree
        for backend in ("host", "tpu_roundtrip"):
            with FileReader(out, backend=backend) as r:
                rows = [x["i"] for x in r.iter_rows()]
            assert rows == list(range(6_700)), backend

    def test_chunk_bytes_verbatim(self, tmp_path):
        """The page bytes in the merged file are IDENTICAL to the source's
        (no re-encoding): compare the first chunk's byte range."""
        from parquet_tpu.core.chunk import chunk_byte_range

        p1 = str(tmp_path / "a.parquet")
        _make(p1, 0, 3_000, compression="zstd")
        out = str(tmp_path / "m.parquet")
        merge_files(out, [p1, p1])  # self-merge doubles the file
        with FileReader(p1) as src, FileReader(out) as dst:
            assert dst.num_row_groups == 2 * src.num_row_groups
            s_cc = src.metadata.row_groups[0].columns[0]
            for g in range(2):
                d_cc = dst.metadata.row_groups[g * src.num_row_groups].columns[0]
                so, sn = chunk_byte_range(s_cc)
                do, dn = chunk_byte_range(d_cc)
                assert sn == dn
                with open(p1, "rb") as f:
                    f.seek(so)
                    src_bytes = f.read(sn)
                with open(out, "rb") as f:
                    f.seek(do)
                    assert f.read(dn) == src_bytes

    def test_merged_output_remerges_and_stats_survive(self, tmp_path):
        p1 = str(tmp_path / "a.parquet")
        _make(p1, 0, 2_000)
        m1 = str(tmp_path / "m1.parquet")
        merge_files(m1, [p1])
        m2 = str(tmp_path / "m2.parquet")
        merge_files(m2, [m1, p1])
        with FileReader(m2) as r:
            # statistics carried verbatim: row-group pruning still works
            assert r.prune_row_groups([("i", ">", 10**9)]) == []
            assert len(list(r.iter_rows())) == 4_000

    def test_schema_mismatch_and_empty(self, tmp_path):
        p1 = str(tmp_path / "a.parquet")
        _make(p1, 0, 100)
        p2 = str(tmp_path / "b.parquet")
        pq.write_table(pa.table({"x": pa.array([1.0])}), p2)
        with pytest.raises(ParquetFileError, match="schema mismatch"):
            merge_files(str(tmp_path / "o.parquet"), [p1, p2])
        with pytest.raises(ParquetFileError, match="at least one"):
            merge_files(str(tmp_path / "o.parquet"), [])

    def test_our_writer_inputs_with_nested(self, tmp_path):
        schema = parse_schema(
            "message m { required int64 id; optional group g "
            "{ optional binary name (UTF8); } }"
        )
        paths = []
        for k in range(2):
            p = str(tmp_path / f"w{k}.parquet")
            with FileWriter(p, schema, codec="snappy") as w:
                w.write_rows([
                    {"id": k * 10 + j, "g": None if j % 3 == 0 else {"name": f"x{j}"}}
                    for j in range(10)
                ])
            paths.append(p)
        out = str(tmp_path / "wm.parquet")
        merge_files(out, paths)
        got = pq.read_table(out)
        assert got.column("id").to_pylist() == [j for k in range(2) for j in range(k * 10, k * 10 + 10)]

    def test_cli(self, tmp_path, capsys):
        p1 = str(tmp_path / "a.parquet")
        p2 = str(tmp_path / "b.parquet")
        _make(p1, 0, 500)
        _make(p2, 500, 800)
        out = str(tmp_path / "m.parquet")
        assert tool_main(["merge", out, p1, p2]) == 0
        assert "800 rows" in capsys.readouterr().out
        assert pq.read_table(out).num_rows == 800

    def test_bloom_and_index_sources_merge_clean(self, tmp_path):
        """Inputs carrying page indexes + blooms (regions outside the chunk
        ranges) merge cleanly: those offsets drop, values stay exact."""
        schema = parse_schema("message m { required int64 a; }")
        p = str(tmp_path / "ib.parquet")
        with FileWriter(p, schema, write_page_index=True,
                        bloom_filters=["a"]) as w:
            w.write_column("a", np.arange(5_000, dtype=np.int64))
        out = str(tmp_path / "ibm.parquet")
        merge_files(out, [p, p])
        assert pq.read_table(out).column("a").to_pylist() == (
            list(range(5_000)) + list(range(5_000))
        )
        with FileReader(out) as r:
            cc = r.metadata.row_groups[0].columns[0]
            assert cc.meta_data.bloom_filter_offset is None
            assert cc.column_index_offset is None

    def test_output_must_not_be_an_input(self, tmp_path):
        """Review regression: merging a file into itself must refuse BEFORE
        truncating the source."""
        p1 = str(tmp_path / "a.parquet")
        _make(p1, 0, 100)
        size = __import__("os").path.getsize(p1)
        with pytest.raises(ParquetFileError, match="also an input"):
            merge_files(p1, [p1])
        assert __import__("os").path.getsize(p1) == size  # source intact
        assert pq.read_table(p1).num_rows == 100

    def test_file_offset_zero_convention_preserved(self, tmp_path):
        """Review regression: pyarrow writes ColumnChunk.file_offset=0
        (modern spec); the merged footer must keep 0, not a bogus delta."""
        p1 = str(tmp_path / "a.parquet")
        _make(p1, 0, 200)
        p2 = str(tmp_path / "b.parquet")
        _make(p2, 200, 400)
        out = str(tmp_path / "m.parquet")
        merge_files(out, [p1, p2])
        with FileReader(p1) as src, FileReader(out) as dst:
            src_off = src.metadata.row_groups[0].columns[0].file_offset
            for rg in dst.metadata.row_groups:
                for cc in rg.columns:
                    if not src_off:
                        assert not cc.file_offset


class TestSplitRowGroups:
    """split_row_groups: the converse verbatim-copy lane (parquet-tool
    split --groups)."""

    def test_roundtrip_through_merge(self, tmp_path):
        from parquet_tpu import merge_files
        from parquet_tpu.core.merge import split_row_groups

        src = str(tmp_path / "src.parquet")
        t = _make(src, 0, 9_000, compression="snappy", row_group_size=2_000)
        parts = split_row_groups(src, str(tmp_path / "part_%d.parquet"), 2)
        assert len(parts) == 3  # 5 groups -> 2+2+1
        total = 0
        for p in parts:
            part_rows = pq.read_table(p).num_rows
            total += part_rows
        assert total == 9_000
        # split -> merge reproduces the full logical file
        back = str(tmp_path / "back.parquet")
        merge_files(back, parts)
        got = pq.read_table(back)
        for c in t.column_names:
            assert got.column(c).to_pylist() == t.column(c).to_pylist(), c
        # shared source metadata not corrupted by per-part offset rewrites
        with FileReader(src) as r:
            assert len(list(r.iter_rows())) == 9_000

    def test_cli_groups_mode(self, tmp_path, capsys):
        src = str(tmp_path / "s.parquet")
        _make(src, 0, 4_000, row_group_size=1_000)
        assert tool_main(
            ["split", "--groups", "2", src, str(tmp_path / "p_%d.parquet")]
        ) == 0
        assert "no re-encoding" in capsys.readouterr().out
        assert pq.read_table(str(tmp_path / "p_1.parquet")).num_rows == 2_000
        assert tool_main(
            ["split", "--groups", "1", "-n", "5", src, str(tmp_path / "q_%d.parquet")]
        ) == 2
